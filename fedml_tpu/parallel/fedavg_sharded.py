"""Sharded (multi-chip) FedAvg round — the distributed runtime.

The reference's distributed FedAvg is a server FSM + N client processes over
MPI, exchanging full state dicts as JSON lists each round (SURVEY §3.1:
FedAvgServerManager.py:34-72, message.py:47-59). Here the whole round is ONE
SPMD program over a `Mesh(("clients",))`:

- broadcast w_t   -> parameters enter `shard_map` with spec P() (replicated —
                     XLA materialises the broadcast over ICI once)
- local training  -> each shard vmaps the jitted local-train scan over its
                     C/n_shards clients (ref HOT LOOP #2)
- upload+aggregate-> weighted partial sums + `psum` over the client axis
                     (ref HOT LOOP #3, FedAVGAggregator.py:51-78's Python
                     per-key loop, and the MPI gather it sits on)

No host round-trip, no serialization, no 0.3 s poll loop
(mpi com_manager.py:71-80). Works identically on a virtual CPU mesh."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg import (
    FedAvgAPI,
    client_axis_map,
    resolve_client_parallelism,
    round_client_rngs,
)
from fedml_tpu.algorithms.fednova import FedNovaAPI
from fedml_tpu.algorithms.fedopt import FedOptAPI
from fedml_tpu.algorithms.ditto import DittoAPI
from fedml_tpu.algorithms.scaffold import ScaffoldAPI
from fedml_tpu.privacy.dp_fedavg import DPFedAvgAPI
from fedml_tpu.config import RunConfig
from fedml_tpu.data.base import ClientBatch, FederatedDataset
from fedml_tpu.models import ModelDef
from fedml_tpu.parallel.mesh import make_mesh, pad_client_batch
from fedml_tpu.train.client import make_local_train


def make_sharded_fedavg_round(
    model: ModelDef,
    config: RunConfig,
    mesh: Mesh,
    task: str = "classification",
    local_train_fn: Optional[Callable] = None,
    donate: bool = True,
    post_train: Optional[Callable] = None,
    post_aggregate: Optional[Callable] = None,
    aggregate_fn: Optional[Callable] = None,
    n_extra: int = 0,
    robust=None,
):
    """Build the jitted sharded round function.

    Returned fn: ``(global_vars, x, y, mask, num_samples, client_rngs,
    *extra) -> (global_vars', metrics)`` where the leading client axis of
    the data args is sharded over the mesh and C % mesh_size == 0 (use
    :func:`pad_client_batch`). ``client_rngs`` is [C, 2]-shaped PRNG key data,
    one key per client, so per-client randomness is identical regardless of
    mesh size (same-seed single-chip and 8-shard runs bit-match — the
    mesh-invariance test relies on this).

    The hook triple mirrors :func:`make_fedavg_round` exactly (same
    signatures, same semantics), so one defense/variant definition serves
    both runtimes. ``n_extra`` replicated trailing args (e.g. a noise rng)
    are forwarded to both hooks. ``aggregate_fn`` replaces the weighted
    psum; because the Byzantine aggregators are order statistics over the
    FULL client axis, the skeleton ``all_gather``s the client updates over
    ICI and hands the aggregate_fn the same stacked view the vmap runtime
    gives it — equality by construction."""
    axis = mesh.axis_names[0]
    if robust is not None:
        # describable defense config instead of opaque hook closures —
        # same contract as make_fedavg_round(robust=): the hooks derive
        # from the digested RobustConfig, so the robust SHARDED round is
        # a first-class cached program too
        if any(h is not None for h in (post_train, post_aggregate, aggregate_fn)):
            raise ValueError(
                "pass either robust= (describable defense config) or "
                "explicit hook closures, not both"
            )
        from fedml_tpu.algorithms.fedavg_robust import make_defense_hooks

        post_train, post_aggregate, aggregate_fn = make_defense_hooks(robust)
    # The client schedule matters on the mesh too: each shard runs its
    # C/n_shards clients, and under vmap their per-client weights turn the
    # convs into grouped convs (the single-chip 1.8x ResNet finding,
    # docs/PERF_R3.md §2). "scan" runs the shard's clients sequentially
    # with full MXU tiling. skip_empty_steps stays off here: lax.cond
    # branch types under shard_map's varying-axes rules don't admit the
    # constant-zero skip branch (padded steps remain where-gated no-ops).
    mode = resolve_client_parallelism(config.fed.client_parallelism, model)
    local_train = local_train_fn or make_local_train(
        model, config.train, config.fed.epochs, task=task
    )
    lifted = client_axis_map(local_train, mode)

    def shard_body(global_vars, x, y, mask, num_samples, client_rngs, *extra):
        # Params enter replicated (spec P()); mark them device-varying so the
        # local-train scan carry (params mixed with sharded data) type-checks
        # under shard_map's varying-manual-axes rules.
        global_vars = jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, (axis,), to="varying"), global_vars
        )
        client_vars, metrics = lifted(global_vars, x, y, mask, client_rngs)
        if post_train is not None:
            client_vars = post_train(client_vars, global_vars, *extra)
        if aggregate_fn is not None:
            gathered = jax.tree_util.tree_map(
                lambda p: jax.lax.all_gather(p, axis, tiled=True), client_vars
            )
            ns_all = jax.lax.all_gather(num_samples, axis, tiled=True)
            new_global = aggregate_fn(gathered, ns_all, global_vars)
        else:
            # Weighted partial sum on this shard, then one psum over ICI.
            wsum = jax.lax.psum(jnp.sum(num_samples), axis)
            new_global = jax.tree_util.tree_map(
                lambda p: jax.lax.psum(
                    jnp.tensordot(num_samples, p.astype(jnp.float32), axes=1),
                    axis,
                )
                / wsum,
                client_vars,
            )
        if post_aggregate is not None:
            new_global = post_aggregate(new_global, *extra)
        agg_metrics = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(jnp.sum(m), axis), metrics
        )
        return new_global, agg_metrics

    data_spec = P(axis)

    def builder():
        sharded = jax.shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(),) + (data_spec,) * 5 + (P(),) * n_extra,
            out_specs=(P(), P()),
            # the all_gather-ed aggregate is replicated by construction (every
            # shard reduces the same gathered stack), which static VMA
            # inference cannot see
            check_vma=aggregate_fn is None,
        )
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())

    # Program dedup (fedml_tpu/compile/): sharded rounds are keyed by the
    # mesh topology on top of the usual (model, train config, schedule)
    # determinants; opaque hooks bypass the registry.
    from fedml_tpu.compile import (
        get_program_cache,
        hooks_cacheable,
        mesh_fingerprint,
        model_fingerprint,
    )

    cache = get_program_cache()
    cacheable = (
        hooks_cacheable(local_train_fn)
        if robust is not None
        else hooks_cacheable(
            local_train_fn, post_train, post_aggregate, aggregate_fn
        )
    )
    if not cacheable:
        return cache.wrap_uncached("sharded_fedavg_round", builder())
    return cache.get_or_build(
        "sharded_fedavg_round",
        {
            "kind": "sharded_fedavg_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "mode": mode,
            "mesh": mesh_fingerprint(mesh),
            "n_extra": n_extra,
            "donate": donate,
            # RobustConfig (or None) — see make_fedavg_round's digest note
            "robust": robust,
        },
        builder,
    )


class DistributedFedAvgAPI(FedAvgAPI):
    """Multi-chip FedAvg driver (ref FedML_FedAvg_distributed, FedAvgAPI.py:21-27
    + both manager classes). Subclass of the single-chip simulator: the host
    loop (sampling, stacking, metrics, eval) is inherited — including the
    scheduler-backed cohort selection and participation-fault filtering
    (FedConfig.selection/fault_plan, scheduler/): a fault-shrunk cohort is
    just another client-axis size, padded to the mesh like any ragged
    round — and this class only swaps the round function for the shard_map
    version and pads + places each round's batch sharded over the mesh."""

    _use_device_store = False  # batches are padded + sharded from host
    # the shard_map round psum-reduces its metrics — no per-client loss
    # vectors; power_of_choice keeps the cohort-mean signal on the mesh
    _client_loss_vectors = False

    def __init__(
        self,
        config: RunConfig,
        data: FederatedDataset,
        model: ModelDef,
        mesh: Optional[Mesh] = None,
        **kw,
    ):
        self.mesh = mesh or make_mesh(
            config.mesh.client_shards, config.mesh.axis_name
        )
        # pad to the number of shards along the CLIENT axis (the mesh may
        # carry more axes, e.g. a "seq" axis for sequence parallelism)
        self.n_shards = self.mesh.shape[self.mesh.axis_names[0]]
        self._data_sharding = NamedSharding(
            self.mesh, P(self.mesh.axis_names[0])
        )
        super().__init__(config, data, model, **kw)

    def _build_round_fn(self, local_train_fn):
        return make_sharded_fedavg_round(
            self.model,
            self.config,
            self.mesh,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
        )

    def _pad_shard_indices(self, sampled):
        """Pad a sampled-client index vector to the mesh size and shard it
        — the gather/scatter vector of stateful algorithms (SCAFFOLD's
        control rows, Ditto's personal rows). Dummy rows point at client 0
        but train on all-zero masks, so their state deltas are EXACT zeros
        (the local-train step where-gates its whole update on has_data;
        pinned by tests) and the scatter-add ignores them."""
        ids, _ = self._spill_pad_ids(sampled)
        return jax.device_put(ids.astype(np.int32), self._data_sharding)

    def _spill_pad_ids(self, sampled):
        """(host ids padded to the shard count, real count) — ONE place
        owns the pad-to-mesh/dummy-id-0 contract, shared by the in-HBM
        index vector above and the spilled-store host gather/scatter
        (only the real prefix is ever scattered back)."""
        n = len(sampled)
        pad = (self.n_shards - n % self.n_shards) % self.n_shards
        ids = np.zeros((n + pad,), np.int64)
        ids[:n] = np.asarray(sampled, np.int64)
        return ids, n

    def _place_cohort_rows(self, rows):
        """Spilled-store cohort rows -> device, sharded over the client
        axis (stateful-algorithm spill x mesh composition)."""
        return jax.device_put(rows, self._data_sharding)

    def _place_batch(self, batch: ClientBatch, round_rng):
        """Pad the client axis to the mesh size and shard everything over it.
        Dummy (padding) clients get zero keys — their mask is all-zero so
        local training is a gated no-op and their aggregation weight is 0."""
        n_sampled = batch.num_clients
        batch = pad_client_batch(batch, self.n_shards)
        keys = np.asarray(round_client_rngs(round_rng, n_sampled))
        client_rngs = np.zeros(
            (batch.num_clients,) + keys.shape[1:], dtype=keys.dtype
        )
        client_rngs[:n_sampled] = keys
        put = lambda a: jax.device_put(a, self._data_sharding)
        return (
            put(batch.x),
            put(batch.y),
            put(batch.mask),
            put(batch.num_samples),
            put(client_rngs),
        )


class RobustDistributedFedAvgAPI(DistributedFedAvgAPI):
    """fedavg_robust on the multi-chip mesh runtime. Byzantine order
    statistics cannot silently include the zero dummy clients that client-
    axis padding would introduce, so the cohort must divide the mesh."""

    def __init__(self, config, data, model, robust=None, mesh=None, **kw):
        from fedml_tpu.robustness import BYZANTINE_AGGREGATORS, RobustConfig

        self.robust = robust or RobustConfig()
        super().__init__(config, data, model, mesh=mesh, **kw)
        if (
            self.robust.defense_type in BYZANTINE_AGGREGATORS
            and config.fed.client_num_per_round % self.n_shards
        ):
            raise ValueError(
                f"Byzantine aggregation on the mesh needs client_num_per_round "
                f"({config.fed.client_num_per_round}) divisible by the mesh "
                f"({self.n_shards}) — padded dummy clients would corrupt the "
                "order statistics"
            )

    def _build_round_fn(self, local_train_fn):
        return make_sharded_fedavg_round(
            self.model,
            self.config,
            self.mesh,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
            robust=self.robust,
            n_extra=1,  # the replicated noise rng
        )

    def _place_batch(self, batch, round_rng):
        from fedml_tpu.algorithms.fedavg_robust import NOISE_FOLD

        base = super()._place_batch(batch, round_rng)
        return base + (jax.random.fold_in(round_rng, NOISE_FOLD),)


class DistributedDPFedAvgAPI(DPFedAvgAPI, DistributedFedAvgAPI):
    """Client-level DP-FedAvg on the multi-chip mesh runtime. Cooperative
    MRO: DPFedAvgAPI supplies the clip/noise hooks, the RDP ledger, and
    its checkpoint/reporting contract; DistributedFedAvgAPI supplies the
    mesh bootstrap and sharded batch placement (the noise rng rides the
    same _place_batch chain); this class swaps the round for the sharded
    skeleton with a psum uniform mean.

    Mesh padding is harmless here: the DP aggregate divides by the FIXED
    expected cohort and excludes padding rows via its num_samples
    inclusion mask (privacy/dp_fedavg.make_dp_hooks), so realized Poisson
    cohorts need not divide the mesh."""

    def __init__(self, config, data, model, dp=None, mesh=None, **kw):
        from fedml_tpu.privacy import DpConfig

        super().__init__(
            config, data, model, dp=dp or DpConfig(), mesh=mesh, **kw
        )

    def _build_round_fn(self, local_train_fn):
        from fedml_tpu.privacy.dp_fedavg import make_dp_hooks

        # the sharded skeleton all_gathers the full client stack before
        # calling aggregate_fn (same view as the vmap runtime), so the
        # single-chip fixed-denominator aggregate applies unchanged
        post_train, aggregate_fn, post_aggregate = make_dp_hooks(
            self.dp, self.config.fed.client_num_per_round
        )
        return make_sharded_fedavg_round(
            self.model,
            self.config,
            self.mesh,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
            post_train=post_train,
            post_aggregate=post_aggregate,
            aggregate_fn=aggregate_fn,
            n_extra=1,  # the replicated noise rng
        )


class DistributedFedNovaAPI(FedNovaAPI, DistributedFedAvgAPI):
    """FedNova (normalized averaging) on the multi-chip mesh runtime — the
    reference's fednova is standalone-only. Cooperative MRO:
    DistributedFedAvgAPI supplies the mesh bootstrap + sharded batch
    placement; this class only swaps in the sharded FedNova round."""

    def _build_round_fn(self, local_train_fn):
        from fedml_tpu.algorithms.fednova import make_sharded_fednova_round

        return make_sharded_fednova_round(
            self.model,
            self.config,
            self.mesh,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
        )


class DistributedScaffoldAPI(ScaffoldAPI, DistributedFedAvgAPI):
    """SCAFFOLD on the multi-chip mesh runtime (no reference counterpart —
    its SCAFFOLD doesn't exist at all; SURVEY §2b inventories FedNova as
    the closest). Cooperative MRO: DistributedFedAvgAPI supplies the mesh
    bootstrap and sharded batch placement; ScaffoldAPI supplies the
    control-variate state and train_round; this class swaps in the
    shard_map round and shards the gather/scatter index vector."""

    def _build_scaffold_round(self):
        from fedml_tpu.algorithms.scaffold import make_sharded_scaffold_round

        return make_sharded_scaffold_round(
            self.model, self.config, self.mesh, task=self.task
        )

    def _build_scaffold_cohort_round(self):
        from fedml_tpu.algorithms.scaffold import (
            make_sharded_scaffold_cohort_round,
        )

        return make_sharded_scaffold_cohort_round(
            self.model, self.config, self.mesh, task=self.task
        )

    def _place_client_indices(self, sampled):
        return self._pad_shard_indices(sampled)



class DistributedDittoAPI(DittoAPI, DistributedFedAvgAPI):
    """Ditto personalization on the multi-chip mesh runtime (no reference
    counterpart — its inventory has no personalization). Cooperative MRO:
    DistributedFedAvgAPI supplies the mesh bootstrap and sharded batch
    placement; DittoAPI supplies the personal store and train_round; this
    class swaps in the shard_map round and pads/shards the gather/scatter
    index vector (dummy rows train on all-zero masks and contribute
    exact-zero row deltas)."""

    def _build_ditto_round(self):
        from fedml_tpu.algorithms.ditto import make_sharded_ditto_round

        return make_sharded_ditto_round(
            self.model, self.config, self.mesh, self.lam, task=self.task,
            donate=self._donate,
        )

    def _build_ditto_cohort_round(self):
        from fedml_tpu.algorithms.ditto import make_sharded_ditto_cohort_round

        return make_sharded_ditto_cohort_round(
            self.model, self.config, self.mesh, self.lam, task=self.task
        )

    def _place_client_indices(self, sampled):
        return self._pad_shard_indices(sampled)



class DistributedFedOptAPI(FedOptAPI, DistributedFedAvgAPI):
    """FedOpt (server optimizer on the pseudo-gradient, ref
    FedOptAggregator.py:95-117) over the multi-chip mesh runtime.

    Cooperative MRO does all the work: FedOptAPI.train_round wraps the
    round with the jitted server step, DistributedFedAvgAPI supplies the
    shard_map round function and sharded batch placement. Donation is off
    (FedOptAPI._donate) because the server step reads the pre-round params
    after the round call."""
