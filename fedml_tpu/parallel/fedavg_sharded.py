"""Sharded (multi-chip) FedAvg round — the distributed runtime.

The reference's distributed FedAvg is a server FSM + N client processes over
MPI, exchanging full state dicts as JSON lists each round (SURVEY §3.1:
FedAvgServerManager.py:34-72, message.py:47-59). Here the whole round is ONE
SPMD program over a `Mesh(("clients",))`:

- broadcast w_t   -> parameters enter `shard_map` with spec P() (replicated —
                     XLA materialises the broadcast over ICI once)
- local training  -> each shard vmaps the jitted local-train scan over its
                     C/n_shards clients (ref HOT LOOP #2)
- upload+aggregate-> weighted partial sums + `psum` over the client axis
                     (ref HOT LOOP #3, FedAVGAggregator.py:51-78's Python
                     per-key loop, and the MPI gather it sits on)

No host round-trip, no serialization, no 0.3 s poll loop
(mpi com_manager.py:71-80). Works identically on a virtual CPU mesh."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg import FedAvgAPI, round_client_rngs
from fedml_tpu.algorithms.fedopt import FedOptAPI
from fedml_tpu.config import RunConfig
from fedml_tpu.data.base import ClientBatch, FederatedDataset
from fedml_tpu.models import ModelDef
from fedml_tpu.parallel.mesh import make_mesh, pad_client_batch
from fedml_tpu.train.client import make_local_train


def make_sharded_fedavg_round(
    model: ModelDef,
    config: RunConfig,
    mesh: Mesh,
    task: str = "classification",
    local_train_fn: Optional[Callable] = None,
    donate: bool = True,
):
    """Build the jitted sharded round function.

    Returned fn: ``(global_vars, x, y, mask, num_samples, client_rngs) ->
    (global_vars', metrics)`` where the leading client axis of the data args
    is sharded over the mesh and C % mesh_size == 0 (use
    :func:`pad_client_batch`). ``client_rngs`` is [C, 2]-shaped PRNG key data,
    one key per client, so per-client randomness is identical regardless of
    mesh size (same-seed single-chip and 8-shard runs bit-match — the
    mesh-invariance test relies on this)."""
    axis = mesh.axis_names[0]
    local_train = local_train_fn or make_local_train(
        model, config.train, config.fed.epochs, task=task
    )

    def shard_body(global_vars, x, y, mask, num_samples, client_rngs):
        # Params enter replicated (spec P()); mark them device-varying so the
        # local-train scan carry (params mixed with sharded data) type-checks
        # under shard_map's varying-manual-axes rules.
        global_vars = jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, (axis,), to="varying"), global_vars
        )
        client_vars, metrics = jax.vmap(
            local_train, in_axes=(None, 0, 0, 0, 0)
        )(global_vars, x, y, mask, client_rngs)
        # Weighted partial sum on this shard, then one psum over ICI.
        wsum = jax.lax.psum(jnp.sum(num_samples), axis)
        new_global = jax.tree_util.tree_map(
            lambda p: jax.lax.psum(
                jnp.tensordot(num_samples, p.astype(jnp.float32), axes=1), axis
            )
            / wsum,
            client_vars,
        )
        agg_metrics = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(jnp.sum(m), axis), metrics
        )
        return new_global, agg_metrics

    data_spec = P(axis)
    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), data_spec, data_spec, data_spec, data_spec, data_spec),
        out_specs=(P(), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


class DistributedFedAvgAPI(FedAvgAPI):
    """Multi-chip FedAvg driver (ref FedML_FedAvg_distributed, FedAvgAPI.py:21-27
    + both manager classes). Subclass of the single-chip simulator: the host
    loop (sampling, stacking, metrics, eval) is inherited; this class only
    swaps the round function for the shard_map version and pads + places each
    round's batch sharded over the mesh."""

    _use_device_store = False  # batches are padded + sharded from host

    def __init__(
        self,
        config: RunConfig,
        data: FederatedDataset,
        model: ModelDef,
        mesh: Optional[Mesh] = None,
        **kw,
    ):
        self.mesh = mesh or make_mesh(
            config.mesh.client_shards, config.mesh.axis_name
        )
        # pad to the number of shards along the CLIENT axis (the mesh may
        # carry more axes, e.g. a "seq" axis for sequence parallelism)
        self.n_shards = self.mesh.shape[self.mesh.axis_names[0]]
        self._data_sharding = NamedSharding(
            self.mesh, P(self.mesh.axis_names[0])
        )
        super().__init__(config, data, model, **kw)

    def _build_round_fn(self, local_train_fn):
        return make_sharded_fedavg_round(
            self.model,
            self.config,
            self.mesh,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
        )

    def _place_batch(self, batch: ClientBatch, round_rng):
        """Pad the client axis to the mesh size and shard everything over it.
        Dummy (padding) clients get zero keys — their mask is all-zero so
        local training is a gated no-op and their aggregation weight is 0."""
        n_sampled = batch.num_clients
        batch = pad_client_batch(batch, self.n_shards)
        keys = np.asarray(round_client_rngs(round_rng, n_sampled))
        client_rngs = np.zeros(
            (batch.num_clients,) + keys.shape[1:], dtype=keys.dtype
        )
        client_rngs[:n_sampled] = keys
        put = lambda a: jax.device_put(a, self._data_sharding)
        return (
            put(batch.x),
            put(batch.y),
            put(batch.mask),
            put(batch.num_samples),
            put(client_rngs),
        )


class DistributedFedOptAPI(FedOptAPI, DistributedFedAvgAPI):
    """FedOpt (server optimizer on the pseudo-gradient, ref
    FedOptAggregator.py:95-117) over the multi-chip mesh runtime.

    Cooperative MRO does all the work: FedOptAPI.train_round wraps the
    round with the jitted server step, DistributedFedAvgAPI supplies the
    shard_map round function and sharded batch placement. Donation is off
    (FedOptAPI._donate) because the server step reads the pre-round params
    after the round call."""
