"""Cross-process hierarchical FL: one OS process per edge group, cloud
aggregation bridged over gRPC — the DCN analog this environment can
actually execute.

THE TWO-LEVEL LAYOUT (scaling-book recipe, docs/MULTIHOST.md): heavy
per-round client aggregation rides the innermost axis (ICI — here each
process's local device mesh / vmap round), while the rare cross-group
cloud sync rides the outermost transport (DCN — here gRPC between
processes, the reference's edge-server topology:
fedml_api/standalone/hierarchical_fl/trainer.py:43-69, where group
trainers are objects in one process; its distributed runtime never
shipped a cross-host hierarchy at all).

Why gRPC and not ``jax.distributed``: on this image the coordination
service DOES form the process group (np=2 on both ranks) but the CPU
PJRT client never federates the device topology — ``jax.device_count()``
stays 1 and per-process device-count knobs are ignored once
``jax.distributed.initialize`` has run. That blocker is pinned by
tests/test_multihost_bridge.py::test_jax_distributed_cpu_blocker_is_pinned;
if it ever flips green, parallel/multihost.initialize_multihost opens the
native path over the same mesh-axis-name contract and this bridge remains
the transport-level fallback.

Protocol (per global round r):
  every rank g computes its group's ``group_comm_round`` sub-rounds via
  HierarchicalFedAvgAPI._group_round — the SAME method the in-process
  simulator runs, so bridged == simulated is an equality, not an analogy;
  rank g>0 sends (model, weight, r) to rank 0; rank 0 stacks its own and
  all received group models, weighted-averages (groups with no sampled
  members contribute weight 0 and no model), and broadcasts the new
  global. Messages ride the binary envelope (core/message.py — dtype
  exact, no JSON lists).
"""

from __future__ import annotations

import queue
import threading
from typing import Optional, Sequence

import jax
import numpy as np

from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvgAPI
from fedml_tpu.core.comm import Observer
from fedml_tpu.core.grpc_comm import GrpcCommManager
from fedml_tpu.core.message import Message

MT_GROUP = "hier_group_model"
MT_GLOBAL = "hier_global_model"


class _Inbox(Observer):
    def __init__(self):
        self.q: "queue.Queue[Message]" = queue.Queue()

    def receive_message(self, msg_type: str, msg: Message) -> None:
        self.q.put(msg)


def _host_tree(tree):
    """Device pytree -> host pytree; the Message envelope serializes param
    pytrees directly (dtype-exact), same as fedavg_transport's model
    broadcasts — no hand-rolled flatten/unflatten layer."""
    return jax.tree_util.tree_map(np.asarray, jax.device_get(tree))


def run_hierarchical_grpc_group(
    config,
    data,
    model,
    rank: int,
    *,
    groups: Optional[Sequence[np.ndarray]] = None,
    base_port: int = 8890,
    log_fn=None,
    recv_timeout_s: float = 300.0,
):
    """Run one edge-group process of a bridged hierarchical federation.

    ``rank`` 0 is cloud + group 0; ranks 1..G-1 are groups. Every process
    constructs the same API (same seed => same group assignment and
    sub-round math) and executes only its own group. Returns the API with
    the final global model (identical on every rank)."""
    api = HierarchicalFedAvgAPI(config, data, model, groups=groups)
    G = len(api.groups)
    if not 0 <= rank < G:
        raise ValueError(f"rank {rank} outside the {G}-group federation")
    comm = GrpcCommManager(
        rank, {i: "127.0.0.1" for i in range(G)}, base_port=base_port
    )
    inbox = _Inbox()
    comm.add_observer(inbox)
    rx = threading.Thread(target=comm.handle_receive_message, daemon=True)
    rx.start()

    def recv(expect_type: str, expect_round: int) -> Message:
        while True:
            try:
                msg = inbox.q.get(timeout=recv_timeout_s)
            except queue.Empty:
                raise RuntimeError(
                    f"rank {rank}: timed out after {recv_timeout_s:.0f}s "
                    f"waiting for {expect_type} round {expect_round} — a "
                    "peer process likely died"
                ) from None
            if (
                msg.get_type() == expect_type
                and int(msg.get("round")) == expect_round
            ):
                return msg
            # late/duplicate deliveries of older rounds are dropped; a
            # FUTURE round would mean a protocol bug — fail loudly
            if int(msg.get("round")) > expect_round:
                raise RuntimeError(
                    f"rank {rank}: got {msg.get_type()} for round "
                    f"{msg.get('round')} while waiting on {expect_round}"
                )

    try:
        for r in range(config.fed.comm_round):
            # every bridge process derives the round's cohort through its
            # OWN api's scheduler: deterministic in (seed, round, config)
            # — the per-process loss/health stores are never fed here, so
            # all processes agree by construction
            sampled = api._sample_clients(r)
            sampled_set = set(int(i) for i in sampled)
            w_group, weight, metrics = api._group_round(
                r, rank, api.groups[rank], sampled_set
            )
            if rank == 0:
                # keyed by sender rank, then averaged in GROUP-INDEX order
                # — message-arrival order is nondeterministic for G>2 and
                # would reorder the float32 weighted sum away from the
                # simulator's fixed group order (the equality contract)
                by_rank = {0: (w_group, weight)} if w_group is not None else {}
                for _ in range(G - 1):
                    msg = recv(MT_GROUP, r)
                    if float(msg.get("weight")) > 0:
                        by_rank[msg.get_sender_id()] = (
                            msg.get("model"),
                            float(msg.get("weight")),
                        )
                in_order = [by_rank[g] for g in sorted(by_rank)]
                api.global_vars = api._cloud_average(
                    [w for w, _ in in_order], [wt for _, wt in in_order]
                )
                global_host = _host_tree(api.global_vars)
                for peer in range(1, G):
                    out = Message(MT_GLOBAL, 0, peer)
                    out.add_params("round", r)
                    out.add_params("model", global_host)
                    comm.send_message(out)
            else:
                out = Message(MT_GROUP, rank, 0)
                out.add_params("round", r)
                out.add_params("weight", float(weight))
                if w_group is not None:
                    out.add_params("model", _host_tree(w_group))
                comm.send_message(out)
                msg = recv(MT_GLOBAL, r)
                api.global_vars = msg.get("model")
            if log_fn is not None and metrics is not None:
                row = {
                    "round": r,
                    "rank": rank,
                    "group_weight": weight,
                    "loss_sum": float(np.asarray(metrics["loss_sum"])),
                }
                log_fn(row)
    finally:
        comm.stop_receive_message()
        rx.join(timeout=5.0)
    return api
