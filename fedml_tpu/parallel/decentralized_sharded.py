"""Mesh-sharded decentralized gossip — topology mixing as ppermutes.

SURVEY §2g maps the reference's decentralized neighbor averaging
(decentralized_worker_manager.py:41-46, standalone client_dsgd.py) to
"sparse collective/permute patterns" on TPU; this module is that mapping.
Workers live one-per-shard on a mesh axis. Any N×N mixing matrix W
decomposes into cyclic-offset bands

    W = Σ_d diag(w_d) · P_d ,   w_d[i] = W[i, (i+d) mod N]

where P_d is the cyclic shift by d — so one gossip step is one
``lax.ppermute`` per REALIZED band (ring+random-link topologies from
partition/topology.py have only a handful), each a pure ICI
neighbor-exchange with no gather and no host round-trip. The whole online
run (T streaming iterations of local SGD + gossip, ref
decentralized_fl_api.py:20-99) is a single jitted ``shard_map``-ed
``lax.scan``.

Math parity: identical to algorithms/decentralized.py's dense-einsum
simulator (the equality test runs both); Push-Sum mixes with Wᵀ for the
same column-stochasticity reason documented there.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.decentralized import _binary_loss
from fedml_tpu.models import ModelDef


def cyclic_decompose(W: np.ndarray) -> Tuple[List[int], np.ndarray]:
    """W → (offsets, weights [N, n_offsets]) with only realized bands kept.
    offsets[0] is always 0 (self weight; may be the zero vector)."""
    W = np.asarray(W, np.float32)
    N = W.shape[0]
    idx = np.arange(N)
    offsets, cols = [0], [W[idx, idx]]
    for d in range(1, N):
        w_d = W[idx, (idx + d) % N]
        if np.any(w_d != 0):
            offsets.append(d)
            cols.append(w_d)
    return offsets, np.stack(cols, axis=1)


def make_sharded_decentralized_run(
    model: ModelDef,
    mixing_matrix: np.ndarray,
    mesh: Mesh,
    lr: float,
    wd: float = 0.0,
    variant: str = "dsgd",
    loss_fn: Optional[Callable] = None,
):
    """Build ``run(stacked_params, x, y) -> (final_params, per_iter_loss)``
    with the worker axis sharded over ``mesh`` (one worker per shard).

    Same signature/semantics as algorithms/decentralized.py's
    make_decentralized_run: x [N, T, *feat], y [N, T].
    """
    if variant not in ("dsgd", "pushsum"):
        raise ValueError(f"variant must be 'dsgd' or 'pushsum', got {variant!r}")
    axis = mesh.axis_names[0]
    N = int(np.asarray(mixing_matrix).shape[0])
    if mesh.shape[axis] != N:
        raise ValueError(
            f"workers ({N}) must equal mesh shards ({mesh.shape[axis]}) — "
            "one gossip worker per shard"
        )
    W = np.asarray(mixing_matrix, np.float32)
    if variant == "pushsum":
        W = W.T  # column-stochastic push (see algorithms/decentralized.py)
    offsets, weights = cyclic_decompose(W)  # weights [N, n_offsets]
    perms = {
        d: [(s, (s - d) % N) for s in range(N)] for d in offsets if d != 0
    }
    loss_fn = loss_fn or _binary_loss(model)
    grad_fn = jax.value_and_grad(loss_fn)

    def mix(tree, w_local):
        """one gossip step: self band + one ppermute per neighbor band."""
        mixed = jax.tree_util.tree_map(lambda p: p * w_local[0], tree)
        for k, d in enumerate(offsets[1:], start=1):
            shifted = jax.tree_util.tree_map(
                lambda p: jax.lax.ppermute(p, axis, perms[d]), tree
            )
            mixed = jax.tree_util.tree_map(
                lambda m, s: m + w_local[k] * s, mixed, shifted
            )
        return mixed

    def shard_body(stacked_params, w_cols, x, y):
        # local shapes carry the worker axis at size 1 — drop it
        sq = lambda a: a.reshape(a.shape[1:])
        params = jax.tree_util.tree_map(sq, stacked_params)
        w_local = w_cols.reshape(-1)  # [n_offsets]
        x_l, y_l = sq(x), sq(y)
        T = x_l.shape[0]

        def step(carry, t):
            params, omega = carry
            loss, grads = grad_fn(params, x_l[t][None], y_l[t][None])
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * (g + wd * p), params, grads
            )
            params = mix(params, w_local)
            if variant == "pushsum":
                omega = mix(omega, w_local)
            return (params, omega), jax.lax.pmean(loss, axis)

        # per-worker scalar: mark varying so the scan carry type matches
        # after the (worker-varying) mix updates it
        omega0 = jax.lax.pcast(
            jnp.ones((), jnp.float32), (axis,), to="varying"
        )
        (params, omega), losses = jax.lax.scan(
            step, (params, omega0), jnp.arange(T)
        )
        if variant == "pushsum":
            params = jax.tree_util.tree_map(lambda p: p / omega, params)
        return jax.tree_util.tree_map(lambda p: p[None], params), losses

    spec = P(axis)
    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, P()),
    )
    sharded_jit = jax.jit(sharded)  # fedlint: disable=uncached-jit -- bespoke mesh program closed over the concrete mixing matrix; built once per run
    w_dev = jnp.asarray(weights)

    def run(stacked_params, x, y):
        put = lambda a: jax.device_put(a, NamedSharding(mesh, spec))
        return sharded_jit(
            jax.tree_util.tree_map(put, stacked_params),
            put(w_dev),
            put(jnp.asarray(x)),
            put(jnp.asarray(y, jnp.float32)),
        )

    return run
