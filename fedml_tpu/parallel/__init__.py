"""Device-mesh parallelism: the TPU-native replacement for the reference's
process-per-worker MPI runtime (ref fedml_core/distributed/communication/mpi/ +
fedml_api/distributed/utils/gpu_mapping.py).

Instead of `mpirun -np N+1` processes exchanging JSON-serialized state dicts
(SURVEY §2h), clients are laid out along a mesh axis of a single SPMD program:
"broadcast" is parameter replication, "gather + aggregate" is a weighted `psum`
over ICI. The mesh spec replaces gpu_mapping.yaml."""

from fedml_tpu import _jax_compat

_jax_compat.install()  # jax.shard_map / jax.lax.pcast on older jaxlib

from fedml_tpu.parallel.mesh import make_mesh, pad_client_batch
from fedml_tpu.parallel.fedavg_sharded import (
    make_sharded_fedavg_round,
    DistributedFedAvgAPI,
    DistributedFedNovaAPI,
    DistributedDittoAPI,
    DistributedDPFedAvgAPI,
    DistributedScaffoldAPI,
    DistributedFedOptAPI,
    RobustDistributedFedAvgAPI,
)
from fedml_tpu.parallel.tensor_parallel import make_tp_train_step
from fedml_tpu.parallel.expert_parallel import make_ep_train_step
from fedml_tpu.parallel.pipeline import make_pp_train_step
from fedml_tpu.parallel.hierarchical_sharded import (
    HierarchicalShardedAPI,
    make_hierarchical_sharded_round,
)
from fedml_tpu.parallel.multihost import (
    hybrid_mesh,
    initialize_multihost,
    mesh_traffic_summary,
)
from fedml_tpu.parallel.decentralized_sharded import (
    make_sharded_decentralized_run,
)

__all__ = [
    "make_mesh",
    "pad_client_batch",
    "make_sharded_fedavg_round",
    "DistributedFedAvgAPI",
    "DistributedFedNovaAPI",
    "DistributedDittoAPI",
    "DistributedDPFedAvgAPI",
    "DistributedScaffoldAPI",
    "DistributedFedOptAPI",
    "RobustDistributedFedAvgAPI",
    "make_tp_train_step",
    "make_ep_train_step",
    "make_pp_train_step",
    "HierarchicalShardedAPI",
    "make_hierarchical_sharded_round",
    "hybrid_mesh",
    "initialize_multihost",
    "mesh_traffic_summary",
    "make_sharded_decentralized_run",
]
