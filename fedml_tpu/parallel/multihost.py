"""Multi-host runtime: jax.distributed bootstrap + hybrid DCN×ICI meshes.

The reference scales across hosts with mpirun + NCCL/MPI process groups
(run_fedavg_distributed_pytorch.sh:16-35, fedml_experiments/centralized/
main.py:54-67); every cross-host exchange is an explicit P2P send. The TPU
equivalent is SPMD over a GLOBAL mesh: each host runs the same jitted
program over its local chips, `jax.distributed.initialize` forms the global
device set, and XLA routes collectives over ICI within a slice and DCN
across slices. Nothing else in the framework changes — the sharded round
functions (parallel/fedavg_sharded.py, hierarchical_sharded.py) are written
against mesh axis *names*, so the same code runs on 1 chip, an 8-chip
slice, or a multi-slice pod; only the mesh handed to them differs.

Axis-layout rule (scaling-book recipe): put the axis with the most traffic
innermost (ICI), the rare-sync axis outermost (DCN). For federated
learning that is: per-round client aggregation → ICI; hierarchical FL's
cross-group (cloud) sync every ``group_comm_round`` rounds → DCN.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> bool:
    """Bring this process into the global device set.

    Thin guard around ``jax.distributed.initialize``: no-op (returns False)
    when the run is single-process — nothing is explicitly configured (no
    args, no JAX_COORDINATOR_ADDRESS) and ``auto`` is off — or when
    num_processes == 1, so drivers can call it unconditionally. Replaces
    the reference's ``MPI.COMM_WORLD`` rank/size bootstrap
    (FedAvgAPI.py:14-18) and ``init_process_group("nccl")``.

    ``auto=True`` additionally hands control to jax's cluster auto-detection
    (Cloud TPU pod metadata, SLURM, …) with no explicit arguments, treating
    a detection failure as "single process". It is opt-in rather than the
    default because auto-detection probes environment services — in an
    air-gapped or test environment that probe is wasted work (and this
    container has no egress at all).

    CRITICAL ORDERING: nothing here may touch the XLA backend before
    ``initialize`` — ``jax.devices()`` / ``jax.process_count()`` would
    initialize it, after which ``jax.distributed.initialize`` raises (the
    same init-order pitfall as the dryrun device bootstrap, VERDICT r1 #1).
    ``jax.distributed.is_initialized()`` is backend-free.
    """
    if _distributed_initialized():
        # label even when someone else did the initialize — the telemetry
        # track name should reflect host rank whenever a cluster exists
        _label_telemetry()
        return True
    env_addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    explicit = not (
        coordinator_address is None and env_addr is None and num_processes is None
    )
    if not explicit:
        if not auto:
            return False
        try:
            jax.distributed.initialize()  # cluster auto-detection
        except (RuntimeError, ValueError):
            return False  # no detectable cluster → single process
        return True
    if num_processes == 1:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _label_telemetry()
    return True


def _distributed_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for older jax
    (0.4.x has no such function): the coordination-service client in the
    private global state is the same signal the public API reads. Both
    paths are backend-free (see the CRITICAL ORDERING note above)."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # noqa: BLE001 — private-API drift ⇒ assume uninitialized
        return False


def _label_telemetry() -> None:
    """Name this process's telemetry track after its host rank, so the
    per-host Chrome traces from a multi-host run can be merged in Perfetto
    and still read as host0/host1/… (each host writes its own file into the
    shared --telemetry_dir; span timestamps are epoch-anchored, so the
    merged view lines up on wall clock)."""
    from fedml_tpu.telemetry import get_tracer

    get_tracer().process_label = (
        f"fedml_tpu host{jax.process_index()}/{jax.process_count()}"
    )


def devices_by_host(devices: Optional[Sequence] = None) -> np.ndarray:
    """[n_hosts, devices_per_host] device array, hosts ordered by
    process_index and devices by id within each host. Raises if hosts are
    unevenly populated (a hybrid mesh needs a rectangle)."""
    devs = list(devices if devices is not None else jax.devices())
    hosts: dict = {}
    for d in devs:
        hosts.setdefault(d.process_index, []).append(d)
    counts = {len(v) for v in hosts.values()}
    if len(counts) != 1:
        raise ValueError(
            f"uneven devices per host: { {k: len(v) for k, v in hosts.items()} }"
        )
    rows = [
        sorted(hosts[p], key=lambda d: d.id) for p in sorted(hosts)
    ]
    return np.array(rows)


def hybrid_mesh(
    dcn_axis: str = "groups",
    ici_axis: str = "clients",
    devices: Optional[Sequence] = None,
    dcn_size: Optional[int] = None,
) -> Mesh:
    """2-D mesh with the slow (cross-host DCN) axis outermost and the fast
    (intra-host ICI) axis innermost.

    Multi-process: rows = hosts (process_index), so collectives over
    ``ici_axis`` stay inside a host/slice and only ``dcn_axis`` collectives
    cross DCN. Single-process (simulation, virtual CPU farm): the flat
    device list is folded into ``dcn_size`` rows (default: number of
    distinct process indices, else 1) so the same program shape can be
    exercised without a cluster — pass ``dcn_size`` explicitly to emulate
    an N-slice layout on the 8-device CPU mesh."""
    devs = list(devices if devices is not None else jax.devices())
    if dcn_size is None:
        grid = devices_by_host(devs)
    else:
        if len(devs) % dcn_size:
            raise ValueError(
                f"{len(devs)} devices not divisible into {dcn_size} rows"
            )
        grid = np.array(devs).reshape(dcn_size, len(devs) // dcn_size)
    return Mesh(grid, (dcn_axis, ici_axis))


def mesh_traffic_summary(mesh: Mesh) -> dict:
    """Which axes ride ICI vs DCN — a placement sanity check for drivers
    (the reference's analog is the gpu_mapping.yaml eyeball check). An axis
    crosses DCN iff its collectives span more than one process."""
    out = {}
    grid = mesh.devices
    for i, name in enumerate(mesh.axis_names):
        cols = np.moveaxis(grid, i, 0).reshape(grid.shape[i], -1)
        crosses = any(
            len({d.process_index for d in cols[:, j]}) > 1
            for j in range(cols.shape[1])
        )
        out[name] = "dcn" if crosses else "ici"
    return out
