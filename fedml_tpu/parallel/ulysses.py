"""Ulysses-style sequence parallelism — all-to-all head/sequence re-sharding.

The second of the two standard SP schemes (the task's "ring attention OR
all-to-all"; public recipe: DeepSpeed-Ulysses, Jacobs et al. 2023). Where
ring attention keeps the sequence sharded and rotates K/V around the ring
(ring_attention.py), Ulysses re-shards: one `all_to_all` over ICI turns
sequence-sharded [B, T/n, H, D] into head-sharded [B, T, H/n, D], each
device runs ordinary FULL attention on its head subset (so the per-device
compute core can be anything — including the Pallas flash kernel), and a
second all_to_all restores sequence sharding.

Trade-off vs ring: 2 all_to_alls of the whole activation per attention
(bisection-bandwidth-bound, great on ICI) instead of n ppermute hops
(latency-amortised); requires H % n == 0; attention math is completely
local, so causal masking needs no global offsets."""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.parallel.ring_attention import full_attention


def ulysses_attention_sharded(
    q,
    k,
    v,
    axis_name: str,
    causal: bool = False,
    attn_fn: Optional[Callable] = None,
):
    """Per-shard body (call inside shard_map over ``axis_name``).

    q/k/v: [B, T_local, H, D], sequence-sharded. H must divide by the axis
    size (validated by the make_* builders, which know the mesh).
    ``attn_fn(q, k, v, causal=...)`` runs on the gathered [B, T, H_local, D]
    blocks — defaults to full attention; pass a flash-backed callable for
    the Pallas core."""
    attn = attn_fn or full_attention

    # seq-sharded -> head-sharded: split H into n, concatenate along T
    def gather_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    # head-sharded -> seq-sharded: split T into n, concatenate along H
    def scatter_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = gather_seq(q), gather_seq(k), gather_seq(v)
    out = attn(qg, kg, vg, causal=causal)
    return scatter_seq(out).astype(q.dtype)


def make_ulysses_attention(
    mesh: Mesh,
    axis_name: str = "seq",
    causal: bool = False,
    attn_fn: Optional[Callable] = None,
):
    """jit-ready Ulysses attention: [B, T, H, D] inputs sharded on T over
    the mesh axis; output sharded the same way. Same contract as
    :func:`parallel.ring_attention.make_ring_attention`. The head dim must
    divide by ``mesh.shape[axis_name]`` (checked at call time)."""
    n = mesh.shape[axis_name]
    inner = jax.shard_map(
        functools.partial(
            ulysses_attention_sharded,
            axis_name=axis_name,
            causal=causal,
            attn_fn=attn_fn,
        ),
        mesh=mesh,
        in_specs=(
            P(None, axis_name, None, None),
            P(None, axis_name, None, None),
            P(None, axis_name, None, None),
        ),
        out_specs=P(None, axis_name, None, None),
        # pallas_call out_shapes carry no varying-mesh-axes info, so a flash
        # attn_fn would trip check_vma; keep validation ON for the default
        # full-attention core
        check_vma=(attn_fn is None),
    )

    @jax.jit  # fedlint: disable=uncached-jit -- bespoke Ulysses SP attention wrapper closed over the mesh; built once per benchmark run
    def fn(q, k, v):
        if q.shape[2] % n:
            raise ValueError(
                f"ulysses needs num_heads % mesh axis size == 0; got "
                f"H={q.shape[2]}, {axis_name}={n}"
            )
        return inner(q, k, v)

    return fn
