"""Sequence-parallel (SP) causal-LM training — the long-context training
path: the sequence axis of every activation lives on a mesh axis; attention
is ring attention over ICI; the loss is a psum-mean.

Composable with FL: a 2-D Mesh ("clients", "seq") runs FL clients as one
axis and splits each client's long sequences over the other — the layout
SURVEY §2h calls for (collectives ride ICI). This module provides the 1-D
"seq" step used by the flagship long-context trainer and the dryrun."""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.models.transformer import TransformerLM
from fedml_tpu.parallel.ring_attention import ring_attention_sharded
from fedml_tpu.parallel.ulysses import ulysses_attention_sharded


def make_sp_lm(
    vocab_size: int,
    axis_name: str = "seq",
    sp_impl: str = "ring",
    local_attn_fn=None,
    **model_kw,
) -> TransformerLM:
    """TransformerLM wired with sequence-parallel attention over
    ``axis_name`` (must be called inside shard_map). ``sp_impl``: "ring"
    (K/V rotation, ring_attention.py) or "ulysses" (all-to-all head
    re-sharding, ulysses.py; needs num_heads % axis_size == 0).
    ``local_attn_fn`` (ulysses only) replaces the per-device attention core
    on the gathered [B, T, H_local, D] blocks — e.g. a flash-backed callable
    so long sequences never materialise T×T scores."""
    if sp_impl == "ring":
        if local_attn_fn is not None:
            raise ValueError("local_attn_fn is only meaningful for ulysses")
        attn = functools.partial(
            ring_attention_sharded, axis_name=axis_name, causal=True
        )
    elif sp_impl == "ulysses":
        attn = functools.partial(
            ulysses_attention_sharded,
            axis_name=axis_name,
            causal=True,
            attn_fn=local_attn_fn,
        )
    else:
        raise ValueError(f"unknown sp_impl {sp_impl!r} (ring|ulysses)")
    if model_kw.get("moe_experts"):
        # exact global Switch aux under the seq sharding (MoEMLP pmeans the
        # routing stats over this axis before forming the product)
        model_kw.setdefault("moe_stats_axis", axis_name)
    return TransformerLM(vocab_size=vocab_size, attn_fn=attn, **model_kw)


def make_sp_train_step(
    mesh: Mesh,
    vocab_size: int,
    lr: float = 1e-3,
    axis_name: str = "seq",
    sp_impl: str = "ring",
    local_attn_fn=None,
    aux_coef: float = 0.01,
    **model_kw,
):
    """Build (init_fn, step_fn) for sequence-parallel LM training.

    step_fn(params, opt_state, tokens, targets) with tokens/targets
    [B, T] sharded on T over the mesh; params replicated. The loss mean and
    grads are psum'd over the ring — one SPMD program, no host round-trips.
    Pass ``moe_experts=E`` to run MoE blocks under SP (expert weights
    replicated here; shard them over a second mesh axis for true EP×SP).
    ``aux_coef`` weighs the Switch load-balance loss, same knob as
    expert_parallel.make_ep_train_step.
    """
    if sp_impl == "ulysses":
        heads = model_kw.get("num_heads", TransformerLM.num_heads)
        n = mesh.shape[axis_name]
        if heads % n:
            raise ValueError(
                f"ulysses needs num_heads % mesh axis size == 0; got "
                f"num_heads={heads}, {axis_name}={n}"
            )
    model = make_sp_lm(
        vocab_size, axis_name, sp_impl=sp_impl, local_attn_fn=local_attn_fn,
        **model_kw,
    )
    opt = optax.adamw(lr)

    def shard_body(params, opt_state, tokens, targets):
        T_local = tokens.shape[1]
        offset = jax.lax.axis_index(axis_name) * T_local

        def loss_fn(p):
            out = model.apply({"params": p}, tokens, pos_offset=offset)
            if model.moe_experts:
                # (logits, aux): aux is already the exact GLOBAL Switch
                # load-balance loss (MoEMLP pmeans the routing stats over
                # the seq axis), identical on every shard — no reduction
                logits, aux = out
            else:
                logits, aux = out, 0.0
            per_tok = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            )
            # global mean over the full sequence
            s = jax.lax.psum(jnp.sum(per_tok), axis_name)
            n = jax.lax.psum(per_tok.size, axis_name)
            return s / n + aux_coef * aux

        # Under jax's varying-manual-axes semantics the grads of the
        # replicated (P()) params come back shard-varying (shard-local
        # partial sums); the transpose does not reduce them through the
        # custom-VJP norm ops (ops/fused_*.py), so reduce explicitly —
        # this also makes the outputs provably replicated, satisfying the
        # vma checker. Pinned bit-exact vs single-device training in
        # tests/test_ring_attention.py::test_sp_lm_matches_single_device.
        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.lax.psum(grads, axis_name)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    data_spec = P(None, axis_name)
    step = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(), data_spec, data_spec),
        out_specs=(P(), P(), P()),
    )

    def init_fn(rng, example_tokens):
        # init runs OUTSIDE shard_map — stats_axis (a pmean axis) must be
        # unset here; param structure doesn't depend on it
        model_full = TransformerLM(
            vocab_size=vocab_size, **{**model_kw, "moe_stats_axis": None}
        )
        variables = model_full.init({"params": rng}, example_tokens[:, :8])
        params = variables["params"]
        return params, opt.init(params)

    return init_fn, jax.jit(step)  # fedlint: disable=uncached-jit -- bespoke long-context training step closed over mesh/opt; built once per benchmark run
