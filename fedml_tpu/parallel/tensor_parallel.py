"""Tensor parallelism (TP) for the transformer LM — Megatron-style sharding
expressed the XLA-native way: annotate parameter shardings on the mesh and
let GSPMD insert the collectives (the scaling-book recipe), instead of
hand-writing all-reduces.

Layout (mesh axis ``tp``):
- attention qkv kernel  [C, 3C]  → P(None, "tp")   (column / head parallel)
- attention out kernel  [C, C]   → P("tp", None)   (row parallel → psum)
- MLP up kernel         [C, 4C]  → P(None, "tp")
- MLP down kernel       [4C, C]  → P("tp", None)
- embeddings, layernorms, head   → replicated

With this layout each block is two matmul chains that each end in exactly
one all-reduce over ``tp`` (XLA inserts it at the row-parallel matmul),
which is the Megatron communication pattern — but derived by the compiler
from the sharding annotations, so it stays correct under fusion, bf16, and
any mesh shape. Composes with data parallelism over a leading ``dp`` axis
(batch sharded, gradients all-reduced by GSPMD at the psum the optimizer
update induces).

The reference has no TP (SURVEY §2g: TP/SP/EP absent — its biggest model is
a 2-layer LSTM); this module exists because the task's multi-chip contract
and long-context obligation are first-class here.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.parallel.mesh import shardings_from_specs


def tp_param_specs(params, tp_axis: str = "tp"):
    """PartitionSpec tree for TransformerLM params under Megatron TP.

    Rule by parameter path: qkv/mlp_up kernels column-sharded, proj/mlp_down
    kernels row-sharded, everything else (embeddings, biases, layernorms,
    lm head) replicated."""

    def spec(path, leaf):
        names = [getattr(k, "key", getattr(k, "name", "")) for k in path]
        if "kernel" in names:
            if any(n in ("qkv", "mlp_up") for n in names):
                return P(None, tp_axis)
            if any(n in ("proj", "mlp_down") for n in names):
                return P(tp_axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def make_sharded_lm_train_step(
    mesh: Mesh,
    model,
    param_specs_fn,
    loss_fn,
    lr: float = 1e-3,
    dp_axis: Optional[str] = None,
):
    """Shared scaffolding for GSPMD-sharded LM training (TP and EP use it):

    - ``param_specs_fn(params) -> PartitionSpec tree`` fixes the layout;
    - ``loss_fn(model, params, tokens, targets) -> scalar``;
    - returns ``(init_fn, step_fn)``: init initialises on one device and
      ``device_put``s into the layout (adamw m/v are zeros_like(param) so
      they inherit it; scalar state replicates), step is one jitted
      program with tokens/targets replicated (or batch-sharded over
      ``dp_axis``) and GSPMD-inserted collectives.
    """
    opt = optax.adamw(lr)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(model, p, tokens, targets)
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    data_sh = NamedSharding(mesh, P(dp_axis) if dp_axis else P())
    jit_step = jax.jit(step)  # fedlint: disable=uncached-jit -- bespoke TP training step closed over mesh/shardings; built once per benchmark run

    def init_fn(rng, example_tokens):
        params = model.init({"params": rng}, example_tokens[:1, :8])["params"]
        params = jax.device_put(
            params, shardings_from_specs(mesh, param_specs_fn(params))
        )
        return params, opt.init(params)

    def run(params, opt_state, tokens, targets):
        tokens = jax.device_put(tokens, data_sh)
        targets = jax.device_put(targets, data_sh)
        return jit_step(params, opt_state, tokens, targets)

    return init_fn, run


def make_tp_train_step(
    mesh: Mesh,
    vocab_size: int,
    lr: float = 1e-3,
    tp_axis: str = "tp",
    dp_axis: Optional[str] = None,
    **model_kw,
):
    """Build (init_fn, step_fn) for tensor-parallel LM training: params
    carry the Megatron TP layout above and GSPMD inserts the per-block
    all-reduces over ``tp``."""
    # deferred: models.transformer itself imports fedml_tpu.parallel
    # (ring_attention), so a module-level import here would be circular
    from fedml_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=vocab_size, **model_kw)

    def loss_fn(model, p, tokens, targets):
        logits = model.apply({"params": p}, tokens)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        )

    return make_sharded_lm_train_step(
        mesh,
        model,
        lambda params: tp_param_specs(params, tp_axis),
        loss_fn,
        lr=lr,
        dp_axis=dp_axis,
    )
