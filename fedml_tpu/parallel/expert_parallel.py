"""Expert parallelism (EP): Mixture-of-Experts LM with the expert axis
sharded over an ``ep`` mesh axis.

The MoE layer itself (top-1 gate, dense dispatch, Switch aux loss) lives
with the other model components in models/transformer.py (``MoEMLP``,
activated via ``TransformerLM(moe_experts=E)`` — so MoE composes with any
attention core, including the sequence-parallel ones); this module adds
the sharding: expert weights placed P("ep", ...), so GSPMD turns the
final sum over experts into one all-reduce over ``ep`` and each device
holds and computes only its E/K experts — the expert-parallel layout with
compiler-derived collectives. Dense dispatch trades FLOPs for static
shapes; on TPU that is the right default at small expert counts (no
ragged all-to-all, no capacity overflow, MXU saturated); a
capacity-factor all_to_all dispatch is the known upgrade path at large E.

The reference has no MoE/EP (SURVEY §2g); first-class here per the task's
multi-chip contract.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.parallel.tensor_parallel import make_sharded_lm_train_step


def MoELM(vocab_size: int, num_experts: int = 4, embed_dim: int = 64, **kw):
    """TransformerLM configured as an MoE LM (returns (logits, aux))."""
    from fedml_tpu.models.transformer import TransformerLM

    return TransformerLM(
        vocab_size=vocab_size,
        moe_experts=num_experts,
        embed_dim=embed_dim,
        **kw,
    )


def ep_param_specs(params, ep_axis: str = "ep"):
    """Shard every MoE expert weight ([E, ...] leaves named w1/w2 under a
    ``moe`` scope) over ``ep_axis``; everything else replicated."""

    def spec(path, leaf):
        names = [getattr(k, "key", getattr(k, "name", "")) for k in path]
        if any(n in ("w1", "w2") for n in names) and "moe" in names:
            return P(ep_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def make_ep_train_step(
    mesh: Mesh,
    vocab_size: int,
    lr: float = 1e-3,
    ep_axis: str = "ep",
    dp_axis: Optional[str] = None,
    aux_coef: float = 0.01,
    **model_kw,
):
    """Build (init_fn, step_fn) for expert-parallel MoE-LM training.
    Same contract as tensor_parallel.make_tp_train_step."""
    model = MoELM(vocab_size, **model_kw)
    if model.moe_experts % mesh.shape[ep_axis]:
        raise ValueError(
            f"num_experts={model.moe_experts} not divisible by mesh axis "
            f"{ep_axis}={mesh.shape[ep_axis]}"
        )

    def loss_fn(model, p, tokens, targets):
        logits, aux = model.apply({"params": p}, tokens)
        ce = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        )
        return ce + aux_coef * aux

    return make_sharded_lm_train_step(
        mesh,
        model,
        lambda params: ep_param_specs(params, ep_axis),
        loss_fn,
        lr=lr,
        dp_axis=dp_axis,
    )
