"""Hierarchical (cloud-edge-device) FedAvg as ONE two-level SPMD program.

The reference's hierarchical FL (fedml_api/standalone/hierarchical_fl/
{trainer.py:43-69, group.py:24-46}) is a Python loop: per global round,
every group (edge server) runs ``group_comm_round`` FedAvg sub-rounds, then
the cloud averages group models by group sample counts. The host-loop analog
here is algorithms/hierarchical.py. This module is the mesh-native version:
the whole global round — every group's every sub-round — is a single jitted
``shard_map`` program over a 2-D ``Mesh((groups, clients))``:

- group sub-round aggregation = ``psum`` over the inner ``clients`` axis
  ONLY (frequent sync → rides ICI on a hybrid mesh, parallel/multihost.py);
- the cloud average = one ``psum`` over the outer ``groups`` axis per
  global round (rare sync → may ride DCN).

This is exactly the ICI/DCN mapping SURVEY §2g calls for ("maps naturally
to ICI-level psum + DCN-level cross-slice aggregation"). Groups whose
cohort is empty this round keep their model and carry zero weight — parity
with the host loop, which skips them.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg import FedAvgAPI, round_client_rngs
from fedml_tpu.algorithms.hierarchical import resolve_groups
from fedml_tpu.config import RunConfig
from fedml_tpu.data.base import FederatedDataset, bucket_steps, stack_clients
from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import make_local_train


def make_hierarchical_sharded_round(
    model: ModelDef,
    config: RunConfig,
    mesh: Mesh,
    task: str = "classification",
    local_train_fn: Optional[Callable] = None,
    donate: bool = True,
):
    """Build the jitted two-level round function.

    Returned fn: ``(global_vars, x, y, mask, num_samples, client_rngs) ->
    (global_vars', metrics)`` with x [R, G, C, S, B, *feat], y/mask/ns/rngs
    alike — R = group_comm_round sub-rounds, G groups (sharded over the
    outer mesh axis), C client slots per group (sharded over the inner
    axis; pad with mask-0/weight-0 dummies). Per-(group, sub-round) math is
    identical to the host loop's round function at matched batches."""
    gaxis, caxis = mesh.axis_names
    local_train = local_train_fn or make_local_train(
        model, config.train, config.fed.epochs, task=task
    )

    def shard_body(global_vars, x, y, mask, ns, rngs):
        # Params enter replicated; the scan carry becomes per-GROUP state
        # (varying over the group axis) but stays replicated within a group
        # — every sub-round ends in a psum over the client axis, so the
        # carry is clients-invariant by construction and only the group
        # axis needs the varying cast.
        global_vars = jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, (gaxis,), to="varying"), global_vars
        )
        # local shapes carry a size-1 group dim (axis 1) — drop it
        sq = lambda a: a.reshape((a.shape[0],) + a.shape[2:])
        x, y, mask, ns, rngs = (sq(a) for a in (x, y, mask, ns, rngs))

        def sub_round(w_group, per):
            x_r, y_r, m_r, ns_r, k_r = per
            # the local-train scan mixes params with client-sharded data, so
            # params must be clients-varying inside the vmap; the psum below
            # clears that axis again before the carry update
            w_in = jax.tree_util.tree_map(
                lambda a: jax.lax.pcast(a, (caxis,), to="varying"), w_group
            )
            client_vars, mets = jax.vmap(
                local_train, in_axes=(None, 0, 0, 0, 0)
            )(w_in, x_r, y_r, m_r, k_r)
            wsum = jax.lax.psum(jnp.sum(ns_r), caxis)
            has = wsum > 0
            denom = jnp.maximum(wsum, 1e-9)
            w_group = jax.tree_util.tree_map(
                lambda p, old: jnp.where(
                    has,
                    jax.lax.psum(
                        jnp.tensordot(ns_r, p.astype(jnp.float32), axes=1),
                        caxis,
                    )
                    / denom,
                    old,
                ),
                client_vars,
                w_group,
            )
            # local per-shard metric sums only — psum is linear, so the
            # cross-shard reduction happens ONCE after the scan instead of
            # R times on the critical path (R cross-DCN latencies saved)
            return w_group, jax.tree_util.tree_map(jnp.sum, mets)

        w_group, mets = jax.lax.scan(
            sub_round, global_vars, (x, y, mask, ns, rngs)
        )
        mets = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(
                jax.lax.psum(jnp.sum(m, axis=0), caxis), gaxis
            ),
            mets,
        )
        # Cloud aggregation: weight = the group's true sample count this
        # round (cohort is the same across sub-rounds; read sub-round 0) —
        # ref trainer.py:43-69 group-size-weighted average semantics.
        gw = jax.lax.psum(jnp.sum(ns[0]), caxis)
        total = jax.lax.psum(gw, gaxis)
        new_global = jax.tree_util.tree_map(
            lambda p: jax.lax.psum(p * gw, gaxis) / total, w_group
        )
        return new_global, mets

    spec = P(None, gaxis, caxis)
    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), spec, spec, spec, spec, spec),
        out_specs=(P(), P()),
    )

    # program dedup (fedml_tpu/compile/): fedlint uncached-jit caught this
    # factory returning a bare jit object. The sub-round count R and the
    # group/client axis sizes are SHAPE classes (they ride in on the
    # [R, G, C, ...] batch), not program constants — the mesh fingerprint
    # pins the topology. An opaque local_train_fn bypasses the registry.
    from fedml_tpu.compile import (
        get_program_cache,
        mesh_fingerprint,
        model_fingerprint,
    )

    cache = get_program_cache()
    builder = lambda: jax.jit(sharded, donate_argnums=(0,) if donate else ())
    if local_train_fn is not None:
        return cache.wrap_uncached("hierarchical_sharded_round", builder())
    return cache.get_or_build(
        "hierarchical_sharded_round",
        {
            "kind": "hierarchical_sharded_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "mesh": mesh_fingerprint(mesh),
            "donate": donate,
        },
        builder,
    )


class HierarchicalShardedAPI(FedAvgAPI):
    """Two-level FedAvg on a 2-D (groups × clients) mesh.

    Drop-in peer of algorithms/hierarchical.py's host-loop API: same
    round-seeded sampling, same group assignment, same per-(group,
    sub-round) stacking seeds and PRNG streams — so the two produce the
    same models/metrics (the equality test), but here a global round is one
    device program with no host round-trips between sub-rounds."""

    _use_device_store = False
    _supports_fused = False
    # group-loop train_round never consumes the _round_placed stash
    _supports_pipeline = False
    _donate = True

    def __init__(
        self,
        config: RunConfig,
        data: FederatedDataset,
        model: ModelDef,
        mesh: Optional[Mesh] = None,
        groups: Sequence[np.ndarray] = None,
        **kw,
    ):
        if mesh is None:
            from fedml_tpu.parallel.multihost import hybrid_mesh

            mesh = hybrid_mesh(
                "groups", "clients", dcn_size=config.fed.group_num
            )
        self.mesh = mesh
        gaxis, caxis = mesh.axis_names
        self.n_groups = mesh.shape[gaxis]
        self.n_client_shards = mesh.shape[caxis]
        self._data_sharding = NamedSharding(mesh, P(None, gaxis, caxis))
        super().__init__(config, data, model, **kw)
        self.groups = resolve_groups(
            groups, data.num_clients, self.n_groups, config.seed
        )
        if len(self.groups) != self.n_groups:
            raise ValueError(
                f"{len(self.groups)} groups != mesh group axis {self.n_groups}"
            )

    def _build_round_fn(self, local_train_fn):
        return make_hierarchical_sharded_round(
            self.model,
            self.config,
            self.mesh,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
        )

    def train_round(self, round_idx: int):
        cfg = self.config
        R = cfg.fed.group_comm_round
        # scheduler-backed cohort (FedConfig.selection + fault plan),
        # memoized — identical to what the host-loop hierarchical API and
        # the base _round_plan derive for this round
        sampled = self._sample_clients(round_idx)
        sampled_set = set(int(i) for i in sampled)
        cohorts = [
            [int(c) for c in members if int(c) in sampled_set]
            for members in self.groups
        ]
        # one static shape across every group: bucket over the whole round's
        # cohort, pad group client slots to a multiple of the client shards.
        # Full-batch (-1) resolves to the round's max client size so every
        # group shares it (per-group -1 would give ragged bs); a bigger
        # single batch is identical math — the loss is a masked mean.
        all_ns = [len(self.data.client_y[i]) for i in sampled]
        steps, bs, _ = bucket_steps(all_ns, cfg.data.batch_size, cfg.data.pad_bucket)
        if cfg.data.batch_size == -1:
            # re-bucket with the resolved bs so steps follows the same
            # size-class rule stack_clients will apply per group
            steps, bs, _ = bucket_steps(all_ns, bs, cfg.data.pad_bucket)
        cmax = max(max((len(g) for g in cohorts), default=1), 1)
        rem = cmax % self.n_client_shards
        cmax += self.n_client_shards - rem if rem else 0

        feat = self.data.client_x[0].shape[1:]
        lab = self.data.client_y[0].shape[1:]
        G = self.n_groups
        x = np.zeros(
            (R, G, cmax, steps, bs) + feat, dtype=self.data.client_x[0].dtype
        )
        y = np.zeros(
            (R, G, cmax, steps, bs) + lab, dtype=self.data.client_y[0].dtype
        )
        mask = np.zeros((R, G, cmax, steps, bs), dtype=np.float32)
        ns = np.zeros((R, G, cmax), dtype=np.float32)
        key_shape = np.asarray(jax.random.PRNGKey(0)).shape
        key_dtype = np.asarray(jax.random.PRNGKey(0)).dtype
        rngs = np.zeros((R, G, cmax) + key_shape, dtype=key_dtype)
        for gi, g_clients in enumerate(cohorts):
            if not g_clients:
                continue
            n_g = len(g_clients)
            for sub in range(R):
                # exact seed/rng parity with the host-loop API
                # (algorithms/hierarchical.py train_round)
                batch = stack_clients(
                    self.data,
                    g_clients,
                    bs,  # resolved batch size (uniform across groups)
                    seed=cfg.seed * 1_000_003 + round_idx * 131 + gi * 17 + sub,
                    pad_bucket=cfg.data.pad_bucket,
                    force_steps=steps,
                )
                rng = jax.random.fold_in(
                    self.rng, (round_idx + 1) * 1009 + gi * 31 + sub
                )
                x[sub, gi, :n_g] = batch.x
                y[sub, gi, :n_g] = batch.y
                mask[sub, gi, :n_g] = batch.mask
                ns[sub, gi, :n_g] = batch.num_samples
                rngs[sub, gi, :n_g] = np.asarray(
                    round_client_rngs(rng, n_g)
                )
        put = lambda a: jax.device_put(a, self._data_sharding)
        self.global_vars, metrics = self.round_fn(
            self.global_vars, put(x), put(y), put(mask), put(ns), put(rngs)
        )
        return sampled, metrics
