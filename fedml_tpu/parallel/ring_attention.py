"""Ring attention — sequence/context parallelism over a mesh axis.

Green-field for the TPU build: the reference has NO sequence parallelism of
any kind (SURVEY §2g/§5 — its longest sequence is an 80-token Shakespeare
window), but long-context is first-class here. Design follows the public
ring-attention recipe (Liu et al. 2023; jax-ml scaling-book ch. "sharding"):
Q/K/V are sharded along the sequence axis of a Mesh; each device holds one
query block and, over N steps, sees every K/V block as they rotate around
the ring via `jax.lax.ppermute` over ICI. Softmax is computed online
(running max m, normalizer l, accumulator o — the flash-attention
recurrence), so the full T×T score matrix never materializes: memory is
O(T_local²) per device and the N rotations overlap compute with ICI
transfers (XLA pipelines ppermute with the block matmuls).

Exact: matches full attention to fp tolerance (test_ring_attention.py),
including causal masking via global block offsets."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, causal: bool, scale: float, o, m, l):
    """One online-softmax accumulation step.

    q [B, Tq, H, D], k/v [B, Tk, H, D]; o/m/l running state.
    Positions are global: q_off/k_off are the blocks' global start indices.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    # scores [B, H, Tq, Tk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = q_off + jax.lax.iota(jnp.int32, Tq)
        k_pos = k_off + jax.lax.iota(jnp.int32, Tk)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    s_max = jnp.max(s, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m, s_max)
    # all-masked guard: exp of (-inf − -inf); clamp the reference point
    m_safe = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
    alpha = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - m_safe))
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(s <= _NEG_INF, 0.0, p)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v)
    return o_new, m_new, l_new


def ring_attention_sharded(q, k, v, axis_name: str, causal: bool = False):
    """The per-shard body (call inside shard_map over ``axis_name``).

    q, k, v: [B, T_local, H, D] — the local sequence block. Returns the
    attention output with the same shape.
    """
    B, Tq, H, D = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    perm = [(j, (j + 1) % n) for j in range(n)]

    # initial accumulators are constants; mark them device-varying so the
    # fori_loop carry (mixed with sharded q/k/v) type-checks under
    # shard_map's varying-manual-axes rules
    pvary = lambda a: jax.lax.pcast(a, (axis_name,), to="varying")
    o0 = pvary(jnp.zeros((B, H, Tq, D), jnp.float32))
    m0 = pvary(jnp.full((B, H, Tq), _NEG_INF, jnp.float32))
    l0 = pvary(jnp.zeros((B, H, Tq), jnp.float32))
    q_off = my_idx * Tq

    def body(i, carry):
        o, m, l, kk, vv = carry
        # after i rotations, this device holds the block that originated at
        # ring position (my_idx − i) mod n
        k_off = ((my_idx - i) % n) * Tq
        o, m, l = _block_attn(q, kk, vv, q_off, k_off, causal, scale, o, m, l)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return (o, m, l, kk, vv)

    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "seq", causal: bool = False):
    """jit-ready ring attention: [B, T, H, D] inputs sharded on T over the
    mesh axis; output sharded the same way."""
    spec = P(None, axis_name, None, None)
    fn = jax.shard_map(
        functools.partial(
            ring_attention_sharded, axis_name=axis_name, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return jax.jit(fn)  # fedlint: disable=uncached-jit -- bespoke ring-attention kernel wrapper closed over the mesh; built once per benchmark run


def full_attention(q, k, v, causal: bool = False):
    """Reference O(T²) attention for correctness checks."""
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(D, jnp.float32)
    )
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)
