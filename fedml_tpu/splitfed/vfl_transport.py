"""Distributed classical vertical FL over the Message/Observer transport
(ref: fedml_api/distributed/classical_vertical_fl/{vfl_api.py,
guest_trainer.py, host_trainer.py}).

The guest (rank 0) holds the labels and its own feature slice; each host
(rank k ≥ 1) holds party k's disjoint feature columns. Per batch (ref
guest_trainer.train):

1. guest → hosts ``S2C_VFL_BATCH``: the batch index (parties walk the
   SAME drop-partial batch grid over their aligned sample axis, so the
   index is the whole message);
2. host → guest ``C2S_VFL_CONTRIB``: the logit contribution
   h_k = dense(extractor_k(x_k)) (host_trainer.py:43-78), optionally
   int8/int4-quantized;
3. guest sums contributions with its own, takes the loss, and returns
   ``S2C_VFL_GRADS`` carrying ∂L/∂h_k to each host
   (guest_trainer.py:96-126), which backprops through its local stack.

Party numerics run through the digested ProgramCache factories
(:mod:`fedml_tpu.splitfed.programs`); the wire composition matches the
fused :class:`VFLAPI` step to float32 resolution (the fused step's XLA
fusion across the party-sum reorders a handful of flops — pinned at
tiny-atol in tests/test_splitfed.py). Per-rank FIFO delivery guarantees
a host applies batch t's gradients before it sees batch t+1's
announcement, so no barrier message is needed."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import RunConfig
from fedml_tpu.core.comm import BaseCommManager
from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
from fedml_tpu.core.managers import ClientManager, ServerManager
from fedml_tpu.core.message import Message, MessageType as MT
from fedml_tpu.core import compression as CZ
from fedml_tpu.splitfed.codec import ActivationCodec
from fedml_tpu.splitfed.programs import (
    make_vfl_guest_grad,
    make_vfl_party_forward,
    make_vfl_party_update,
)
from fedml_tpu.telemetry import get_comm_meter, get_tracer, wrap_in_current_scope


def _party_params(feature_splits, hidden_dim, out_dim, seed, party_idx):
    """Party ``party_idx``'s init, bit-identical to ``VFLAPI.__init__`` —
    every rank derives the SAME per-party rng fan-out from the shared
    seed, so sim and transport start from one model."""
    from fedml_tpu.algorithms.vertical_fl import VFLParty

    rngs = jax.random.split(jax.random.PRNGKey(seed), len(feature_splits))
    party = VFLParty(
        int(feature_splits[party_idx]),
        hidden_dim,
        out_dim,
        rngs[party_idx],
        has_labels=(party_idx == 0),
    )
    return jax.device_get(party.params)


def _batch_starts(n: int, bs: int) -> List[int]:
    return list(range(0, n - bs + 1, bs))


class VFLGuestManager(ServerManager):
    """Label holder + per-batch FSM (ref guest_trainer.py). Rank 0 = party 0."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        x_guest: np.ndarray,
        y: np.ndarray,
        feature_splits,
        hidden_dim: int = 16,
        out_dim: int = 1,
        log_fn=None,
    ):
        super().__init__(comm, rank=0, config=config)
        self.config = config
        self.x = np.asarray(x_guest)
        self.y = np.asarray(y, np.float32)
        self.feature_splits = tuple(int(d) for d in feature_splits)
        self.n_parties = len(self.feature_splits)
        self.log_fn = log_fn or (lambda m: None)
        lr = config.train.lr
        self.params = _party_params(
            self.feature_splits, hidden_dim, out_dim, config.seed, 0
        )
        import optax

        self._opt = optax.sgd(lr, momentum=0.9)
        self.opt_state = self._opt.init(self.params)
        self._forward = make_vfl_party_forward(hidden_dim, out_dim, True)
        self._guest_grad = make_vfl_guest_grad(self.n_parties, out_dim)
        self._update = make_vfl_party_update(hidden_dim, out_dim, True, lr=lr)
        self._codec = ActivationCodec.from_config(config.comm)
        self._tracer = get_tracer()
        self.round_idx = 0
        self.history: List[dict] = []
        self._starts = _batch_starts(len(self.y), int(config.data.batch_size))
        self._batch = 0
        self._contribs: Dict[int, np.ndarray] = {}
        self._loss_sum = 0.0
        self._correct = 0
        self._round_span = None
        self._federation_done = False

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MT.C2S_VFL_CONTRIB, self._on_contrib)

    def send_init_msg(self):
        self._t0 = time.monotonic()
        self._start_round()

    def _start_round(self):
        r = self.round_idx
        self._batch = 0
        self._loss_sum = 0.0
        self._correct = 0
        self._round_span = self._tracer.start_span("round", round=r)
        if not self._starts:
            self._complete_round()
            return
        self._announce_batch()

    def _announce_batch(self):
        r = self.round_idx
        self._contribs = {}
        with self._tracer.span("broadcast", round=r):
            for host in range(1, self.n_parties):
                msg = Message(MT.S2C_VFL_BATCH, 0, host)
                msg.add_params(MT.ARG_ROUND_IDX, r)
                msg.add_params(MT.ARG_BATCH_IDX, self._batch)
                self.send_message(msg)

    def _on_contrib(self, msg: Message):
        if (
            self._federation_done
            or int(msg.get(MT.ARG_ROUND_IDX)) != self.round_idx
            or int(msg.get(MT.ARG_BATCH_IDX)) != self._batch
        ):
            return
        payload = msg.get(MT.ARG_ACT_PAYLOAD)
        if payload is not None:
            contrib = ActivationCodec.decode(payload, msg.get(MT.ARG_ACT_CODEC))
        else:
            contrib = msg.get(MT.ARG_CONTRIB)
        self._contribs[msg.get_sender_id()] = np.asarray(contrib)
        if len(self._contribs) == self.n_parties - 1:
            self._process_batch()

    def _process_batch(self):
        r = self.round_idx
        s = self._starts[self._batch]
        bs = int(self.config.data.batch_size)
        xb = jnp.asarray(self.x[s : s + bs])
        yb = jnp.asarray(self.y[s : s + bs])
        with self._tracer.span("boundary", round=r):
            own = self._forward(self.params, xb)
            ordered = [own] + [
                jnp.asarray(self._contribs[h]) for h in range(1, self.n_parties)
            ]
            loss, correct, grads = self._guest_grad(ordered, yb)
            self.params, self.opt_state = self._update(
                self.params, self.opt_state, xb, grads[0]
            )
        self._loss_sum += float(loss)
        self._correct += int(correct)
        for host in range(1, self.n_parties):
            g = np.ascontiguousarray(np.asarray(grads[host]))
            out = Message(MT.S2C_VFL_GRADS, 0, host)
            out.add_params(MT.ARG_ROUND_IDX, r)
            out.add_params(MT.ARG_BATCH_IDX, self._batch)
            if self._codec is not None:
                gp = self._codec.encode(f"down:{host}", g)
                get_comm_meter().on_downlink(CZ.payload_bytes(gp), g.nbytes)
                out.add_params(MT.ARG_ACT_PAYLOAD, gp)
                out.add_params(MT.ARG_ACT_CODEC, self._codec.method)
            else:
                get_comm_meter().on_downlink(g.nbytes, g.nbytes)
                out.add_params(MT.ARG_CONTRIB_GRAD, g)
            self.send_message(out)
        self._batch += 1
        if self._batch < len(self._starts):
            self._announce_batch()
        else:
            self._complete_round()

    def _complete_round(self):
        r = self.round_idx
        seen = len(self._starts) * int(self.config.data.batch_size)
        row = {
            "round": r,
            "t_s": round(time.monotonic() - getattr(self, "_t0", time.monotonic()), 3),
            "Train/Loss": self._loss_sum / max(len(self._starts), 1),
            "Train/Acc": self._correct / max(seen, 1),
        }
        self.history.append(row)
        self.log_fn(row)
        if self._round_span is not None:
            self._round_span.end()
            self._round_span = None
        self.round_idx = r + 1
        if self.round_idx >= self.config.fed.comm_round:
            self._federation_done = True
            for host in range(1, self.n_parties):
                self.send_message(Message(MT.FINISH, 0, host))
            self.finish()
        else:
            self._start_round()


class VFLHostManager(ClientManager):
    """Feature-slice holder, party ``rank`` (ref host_trainer.py)."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        rank: int,
        x_host: np.ndarray,
        n_samples: int,
        feature_splits,
        hidden_dim: int = 16,
        out_dim: int = 1,
    ):
        super().__init__(comm, rank, config=config)
        self.config = config
        self.x = np.asarray(x_host)
        lr = config.train.lr
        self.params = _party_params(
            tuple(feature_splits), hidden_dim, out_dim, config.seed, rank
        )
        import optax

        self._opt = optax.sgd(lr, momentum=0.9)
        self.opt_state = self._opt.init(self.params)
        self._forward = make_vfl_party_forward(hidden_dim, out_dim, False)
        self._update = make_vfl_party_update(hidden_dim, out_dim, False, lr=lr)
        self._codec = ActivationCodec.from_config(config.comm)
        self._tracer = get_tracer()
        self._starts = _batch_starts(n_samples, int(config.data.batch_size))
        self._xb = None
        self._pending = None  # (round, batch) awaiting grads

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MT.S2C_VFL_BATCH, self._on_batch)
        self.register_message_receive_handler(MT.S2C_VFL_GRADS, self._on_grads)
        self.register_message_receive_handler(MT.FINISH, lambda m: self.finish())

    def _on_batch(self, msg: Message):
        r = int(msg.get(MT.ARG_ROUND_IDX))
        bi = int(msg.get(MT.ARG_BATCH_IDX))
        s = self._starts[bi]
        bs = int(self.config.data.batch_size)
        self._xb = jnp.asarray(self.x[s : s + bs])
        self._pending = (r, bi)
        with self._tracer.span("forward", round=r):
            contrib = np.ascontiguousarray(np.asarray(self._forward(self.params, self._xb)))
        out = Message(MT.C2S_VFL_CONTRIB, self.rank, 0)
        out.add_params(MT.ARG_ROUND_IDX, r)
        out.add_params(MT.ARG_BATCH_IDX, bi)
        if self._codec is not None:
            payload = self._codec.encode(f"up:{self.rank}", contrib)
            get_comm_meter().on_uplink(CZ.payload_bytes(payload), contrib.nbytes)
            out.add_params(MT.ARG_ACT_PAYLOAD, payload)
            out.add_params(MT.ARG_ACT_CODEC, self._codec.method)
        else:
            get_comm_meter().on_uplink(contrib.nbytes, contrib.nbytes)
            out.add_params(MT.ARG_CONTRIB, contrib)
        self.send_message(out)

    def _on_grads(self, msg: Message):
        key = (int(msg.get(MT.ARG_ROUND_IDX)), int(msg.get(MT.ARG_BATCH_IDX)))
        if self._pending != key:
            return  # stale/duplicate reply
        self._pending = None
        payload = msg.get(MT.ARG_ACT_PAYLOAD)
        if payload is not None:
            g = ActivationCodec.decode(payload, msg.get(MT.ARG_ACT_CODEC))
        else:
            g = msg.get(MT.ARG_CONTRIB_GRAD)
        with self._tracer.span("backward", round=key[0]):
            self.params, self.opt_state = self._update(
                self.params, self.opt_state, self._xb, jnp.asarray(g)
            )


def run_loopback_vfl(
    config: RunConfig,
    xs_parties,
    y,
    hidden_dim: int = 16,
    out_dim: int = 1,
    log_fn=None,
):
    """One-process vertical federation over the loopback hub: guest +
    len(xs_parties)-1 host actors in threads. Returns ``(guest, hosts)``
    so callers can read every party's final params."""
    feature_splits = [int(np.asarray(x).shape[1]) for x in xs_parties]
    hub = LoopbackHub()
    guest = VFLGuestManager(
        config,
        LoopbackCommManager(hub, 0),
        xs_parties[0],
        y,
        feature_splits,
        hidden_dim=hidden_dim,
        out_dim=out_dim,
        log_fn=log_fn,
    )
    hosts = [
        VFLHostManager(
            config,
            LoopbackCommManager(hub, rank),
            rank,
            xs_parties[rank],
            len(y),
            feature_splits,
            hidden_dim=hidden_dim,
            out_dim=out_dim,
        )
        for rank in range(1, len(xs_parties))
    ]
    threads = [
        # bind the spawner's telemetry scope to each host thread — bare
        # h.run would emit this tenant's spans into the global registry
        threading.Thread(
            target=wrap_in_current_scope(h.run), daemon=True,
            name=f"vfl-host-{h.rank}",
        )
        for h in hosts
    ]
    for t in threads:
        t.start()
    guest.send_init_msg()
    guest.run()
    for t in threads:
        t.join(timeout=60)
    return guest, hosts
