"""Split & vertical federation as first-class citizens.

The split-learning (SplitNN ring relay) and classical vertical-FL
(guest/host) runtimes with the same layer stack the horizontal family
has: explicit boundary messages through :class:`BaseCommManager`
(``core/message.py`` S2C_SPLIT_* / *_VFL_*), digested ProgramCache
factories for every boundary-cut and fused program (:mod:`.programs`),
activation-wire compression (:mod:`.codec`), scheduler/fault/serve
integration (:mod:`.split_transport`, :mod:`.vfl_transport`). See
docs/SPLITFED.md.

Transports import lazily (PEP 562) so the compile-layer factories stay
importable from ``algorithms/`` without dragging in the serve stack.
"""

from __future__ import annotations

from fedml_tpu.splitfed.codec import BOUNDARY_CODECS, ActivationCodec
from fedml_tpu.splitfed.programs import (
    make_split_optimizer,
    make_splitnn_client_backward,
    make_splitnn_client_forward,
    make_splitnn_eval,
    make_splitnn_fused_step,
    make_splitnn_server_step,
    make_vfl_fused_step,
    make_vfl_guest_grad,
    make_vfl_party_forward,
    make_vfl_party_update,
    merge_opt_state,
    merge_party_opt_states,
    split_opt_state,
    split_party_opt_states,
    splitnn_cut_spec,
    vfl_spec,
)

_LAZY = {
    "SplitNNServerManager": "fedml_tpu.splitfed.split_transport",
    "SplitNNClientManager": "fedml_tpu.splitfed.split_transport",
    "run_loopback_splitnn": "fedml_tpu.splitfed.split_transport",
    "VFLGuestManager": "fedml_tpu.splitfed.vfl_transport",
    "VFLHostManager": "fedml_tpu.splitfed.vfl_transport",
    "run_loopback_vfl": "fedml_tpu.splitfed.vfl_transport",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)


__all__ = [
    "ActivationCodec",
    "BOUNDARY_CODECS",
    "SplitNNClientManager",
    "SplitNNServerManager",
    "VFLGuestManager",
    "VFLHostManager",
    "make_split_optimizer",
    "make_splitnn_client_backward",
    "make_splitnn_client_forward",
    "make_splitnn_eval",
    "make_splitnn_fused_step",
    "make_splitnn_server_step",
    "make_vfl_fused_step",
    "make_vfl_guest_grad",
    "make_vfl_party_forward",
    "make_vfl_party_update",
    "merge_opt_state",
    "merge_party_opt_states",
    "run_loopback_splitnn",
    "run_loopback_vfl",
    "split_opt_state",
    "split_party_opt_states",
    "splitnn_cut_spec",
    "vfl_spec",
]
