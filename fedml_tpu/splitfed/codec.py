"""Activation-wire compression for the split/vertical boundary.

Per-batch *activations* (and their returned gradients) dominate the
split-learning wire the way per-round weight deltas dominate the
horizontal one, so the boundary composes the same PR-14 codecs
(core/compression.py int8/int4) over them. Two deltas from the model
path:

- activations are **values, not deltas** — there is no reference tree to
  subtract, so the codec quantizes the raw array (quantization error is
  relative to activation magnitude, which the relu'd cut keeps tame);
- error feedback is **per-stream**: each direction of each (client,
  batch-shape) pair keeps its own residual, added into the *next* tensor
  on the same stream before quantizing — the split analogue of the
  per-client residual in :class:`~fedml_tpu.core.compression.ErrorFeedback`.
  Residuals only make sense while the stream's shape is stable; a shape
  change (last partial batch, new round cohort) resets that stream.

Payloads travel as the same flat ``{"n", "q0", "s0", ...}`` dicts the
model path ships, plus a ``"shape"`` key so the receiver can build the
decode template without out-of-band metadata (decoders ignore unknown
keys). Metering happens at the call sites through the existing
``on_uplink``/``on_downlink`` accounting — the cut factor is read off
``comm/*``, never asserted.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from fedml_tpu.core import compression as CZ

# codecs that make sense for dense activation tensors (topk variants are
# delta-sparsity codecs — activations are dense, so they are excluded)
BOUNDARY_CODECS = ("none", "int8", "int4")


class ActivationCodec:
    """Quantize boundary tensors, optionally with per-stream error
    feedback. One instance per endpoint; streams are keyed by the caller
    (e.g. ``"up:3"`` for client 3's uplink)."""

    def __init__(self, method: str, error_feedback: bool = False):
        if method not in BOUNDARY_CODECS or method == "none":
            raise ValueError(
                f"activation codec must be one of {BOUNDARY_CODECS[1:]}, got {method!r}"
            )
        self.method = method
        self.error_feedback = bool(error_feedback)
        self._residual: Dict[str, np.ndarray] = {}

    @classmethod
    def from_config(cls, comm) -> Optional["ActivationCodec"]:
        method = getattr(comm, "activation_compression", "none")
        if method in (None, "", "none"):
            return None
        return cls(method, error_feedback=getattr(comm, "activation_error_feedback", False))

    def encode(self, stream: str, arr) -> Dict[str, np.ndarray]:
        a = np.ascontiguousarray(np.asarray(arr), dtype=np.float32)
        if self.error_feedback:
            res = self._residual.get(stream)
            if res is not None and res.shape == a.shape:
                a = a + res
        payload = CZ.encode_delta(a, self.method)
        if self.error_feedback:
            decoded = CZ.decode_delta(payload, np.zeros_like(a), self.method)
            self._residual[stream] = a - np.asarray(decoded, dtype=np.float32)
        payload = dict(payload)
        payload["shape"] = np.asarray(a.shape, np.int32)
        return payload

    @staticmethod
    def decode(payload: Dict[str, np.ndarray], method: str) -> np.ndarray:
        shape = tuple(int(d) for d in np.asarray(payload["shape"]).tolist())
        template = np.zeros(shape, np.float32)
        return np.asarray(CZ.decode_delta(payload, template, method), dtype=np.float32)
