"""Digested ProgramCache factories for the split/vertical runtimes.

Until PR 19 the split-learning and vertical-FL simulators carried two
standing ``fedlint: disable=uncached-jit`` suppressions: their train
steps were per-API-instance ``jax.jit`` closures over opaque ``self``
state, invisible to the dedup/warmup/executable-store stack. This module
is the replacement wiring point — every split/vertical program (the
fused simulator steps AND the boundary-cut client-forward /
server-step / client-backward programs the transport dispatches) is a
:func:`~fedml_tpu.compile.program_cache.ProgramCache.get_or_build`
factory whose digest pins the full cut spec:

- the **cut-layer spec** — canonical fingerprints of the bottom and top
  ``ModelDef``s (SplitNN) or the party module hyperparameters + feature
  split (VFL), so two tenants cut at different layers can never share a
  trace;
- the **optimizer config** (lr / momentum / weight decay) — baked into
  the traced update, exactly the scaffold-``eta_g`` hazard class the
  digest audit fans out over (analysis/digest_audit.py).

The boundary programs partition the fused step at the wire: the
composition ``client_forward → server_step → client_backward`` over
per-group optimizer states is bit-identical to the fused step over the
joint ``{"bottom", "top"}`` param dict (pinned by
tests/test_splitfed.py — the per-leaf optax transforms partition
exactly, and the vjp cut recomputes the same forward). The opt-state
``merge``/``split`` helpers below are that partition's state-side
inverse pair, used by the serve-layer checkpoint path so a split
tenant's rolling checkpoint carries ONE fused optimizer tree like every
horizontal tenant's."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.compile import get_program_cache, model_fingerprint


def make_split_optimizer(lr: float, momentum: float, wd: float):
    """The ONE split-learning optimizer recipe (ref client.py:18-19 —
    SGD(0.1, momentum=0.9, wd=5e-4)), shared by the fused simulator, the
    boundary programs, and the transport managers so the three can never
    drift: both optax transforms are per-leaf, which is what makes the
    per-group partition of the fused chain exact."""
    return optax.chain(
        optax.add_decayed_weights(wd), optax.sgd(lr, momentum=momentum)
    )


def splitnn_cut_spec(bottom, top, lr: float, momentum: float, wd: float) -> dict:
    """Digest fields shared by every SplitNN program of one cut."""
    return {
        "bottom": model_fingerprint(bottom),
        "top": model_fingerprint(top),
        "opt": {
            "lr": float(lr), "momentum": float(momentum), "wd": float(wd),
        },
    }


def make_splitnn_fused_step(
    bottom, top, lr: float = 0.1, momentum: float = 0.9, wd: float = 5e-4
):
    """The fused simulator step — ``(params, opt_state, x, y) ->
    (params, opt_state, loss, correct)`` over the joint
    ``{"bottom", "top"}`` param dict (jax.grad through the composition IS
    the activation-gradient exchange)."""
    opt = make_split_optimizer(lr, momentum, wd)

    def builder():
        def loss_fn(params, x, y):
            acts, _ = bottom.apply({"params": params["bottom"]}, x, train=True)
            logits, _ = top.apply({"params": params["top"]}, acts, train=True)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            correct = jnp.sum(jnp.argmax(logits, -1) == y)
            return loss, correct

        def step(params, opt_state, x, y):
            (loss, correct), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, x, y)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, correct

        return jax.jit(step)

    return get_program_cache().get_or_build(
        "splitnn_fused_step",
        {"kind": "splitnn_fused_step",
         **splitnn_cut_spec(bottom, top, lr, momentum, wd)},
        builder,
    )


def make_splitnn_client_forward(bottom):
    """Client side of the cut: ``(bottom_params, x) -> acts`` — the
    activations that cross the wire (ref client.py:24-34 forward)."""
    def builder():
        def forward(bottom_params, x):
            return bottom.apply({"params": bottom_params}, x, train=True)[0]

        return jax.jit(forward)

    return get_program_cache().get_or_build(
        "splitnn_client_forward",
        {"kind": "splitnn_client_forward", "bottom": model_fingerprint(bottom)},
        builder,
    )


def make_splitnn_server_step(
    top, lr: float = 0.1, momentum: float = 0.9, wd: float = 5e-4
):
    """Server side of the cut: ``(top_params, top_opt_state, acts, y) ->
    (top_params, top_opt_state, loss, correct, acts_grad)`` — loss +
    top update + the activation gradients returned to the client (ref
    server.py:40-60 loss + acts.grad)."""
    opt = make_split_optimizer(lr, momentum, wd)

    def builder():
        def step(top_params, top_opt_state, acts, y):
            def server_loss(tp, a):
                logits, _ = top.apply({"params": tp}, a, train=True)
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean()
                correct = jnp.sum(jnp.argmax(logits, -1) == y)
                return loss, correct

            (loss, correct), (top_grads, acts_grad) = jax.value_and_grad(
                server_loss, argnums=(0, 1), has_aux=True
            )(top_params, acts)
            updates, top_opt_state = opt.update(
                top_grads, top_opt_state, top_params
            )
            top_params = optax.apply_updates(top_params, updates)
            return top_params, top_opt_state, loss, correct, acts_grad

        return jax.jit(step)

    return get_program_cache().get_or_build(
        "splitnn_server_step",
        {"kind": "splitnn_server_step", "top": model_fingerprint(top),
         "opt": {"lr": float(lr), "momentum": float(momentum), "wd": float(wd)}},
        builder,
    )


def make_splitnn_client_backward(
    bottom, lr: float = 0.1, momentum: float = 0.9, wd: float = 5e-4
):
    """Client backward with the returned activation grads:
    ``(bottom_params, bottom_opt_state, x, acts_grad) ->
    (bottom_params, bottom_opt_state)`` — the vjp recomputes the forward,
    so the client never stores the cut tape across the wire wait."""
    opt = make_split_optimizer(lr, momentum, wd)

    def builder():
        def step(bottom_params, bottom_opt_state, x, acts_grad):
            _, bottom_vjp = jax.vjp(
                lambda p: bottom.apply({"params": p}, x, train=True)[0],
                bottom_params,
            )
            (grads,) = bottom_vjp(acts_grad)
            updates, bottom_opt_state = opt.update(
                grads, bottom_opt_state, bottom_params
            )
            bottom_params = optax.apply_updates(bottom_params, updates)
            return bottom_params, bottom_opt_state

        return jax.jit(step)

    return get_program_cache().get_or_build(
        "splitnn_client_backward",
        {"kind": "splitnn_client_backward", "bottom": model_fingerprint(bottom),
         "opt": {"lr": float(lr), "momentum": float(momentum), "wd": float(wd)}},
        builder,
    )


def make_splitnn_eval(bottom, top):
    """Full-composition eval: ``(bottom_params, top_params, x, y) ->
    correct`` (train=False on both halves, like SplitNNAPI.evaluate)."""
    def builder():
        def ev(bottom_params, top_params, x, y):
            acts, _ = bottom.apply({"params": bottom_params}, x, train=False)
            logits, _ = top.apply({"params": top_params}, acts, train=False)
            return jnp.sum(jnp.argmax(logits, -1) == y)

        return jax.jit(ev)

    return get_program_cache().get_or_build(
        "splitnn_eval",
        {"kind": "splitnn_eval", "bottom": model_fingerprint(bottom),
         "top": model_fingerprint(top)},
        builder,
    )


# -- optimizer-state partition (fused <-> per-group) -----------------------
#
# The fused chain's state over {"bottom": ..., "top": ...} flattens to
# bottom-group leaves followed by top-group leaves (dict keys sort
# "bottom" < "top", and optax transforms are per-leaf) — so the fused
# state and the pair of per-group states are leaf-permutation-free
# re-bracketings of the SAME arrays. merge/split below are exact
# inverses; the serve checkpoint path round-trips through them.


def _group_template(opt, params):
    return jax.eval_shape(opt.init, params)


def merge_opt_state(opt, bottom_state, top_state, bottom_params, top_params):
    """Per-group optimizer states -> the fused chain state over the joint
    ``{"bottom", "top"}`` param dict (the checkpoint representation)."""
    fused_t = _group_template(
        opt, {"bottom": bottom_params, "top": top_params}
    )
    leaves = jax.tree_util.tree_leaves(bottom_state) + (
        jax.tree_util.tree_leaves(top_state)
    )
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(fused_t), leaves
    )


def split_opt_state(opt, fused_state, bottom_params, top_params):
    """Fused chain state -> ``(bottom_state, top_state)`` — the inverse
    of :func:`merge_opt_state`."""
    b_t = _group_template(opt, bottom_params)
    t_t = _group_template(opt, top_params)
    leaves = jax.tree_util.tree_leaves(fused_state)
    nb = len(jax.tree_util.tree_leaves(b_t))
    bottom_state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(b_t), leaves[:nb]
    )
    top_state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(t_t), leaves[nb:]
    )
    return bottom_state, top_state


# -- vertical FL -----------------------------------------------------------


def _vfl_contribution(hidden_dim, out_dim, has_labels, params, x):
    """One party's logit contribution h_k = dense(extractor(x_k)).
    Modules are reconstructed from their hyperparameters (flax linen
    modules are frozen dataclasses — construction is free and apply is
    functional), so the traced program is fully determined by the digest
    fields, never by a party instance."""
    from fedml_tpu.models.vfl import VFLClassifier, VFLFeatureExtractor

    extractor = VFLFeatureExtractor(output_dim=hidden_dim)
    dense = VFLClassifier(output_dim=out_dim, use_bias=has_labels)
    return dense.apply(params["dense"], extractor.apply(params["extractor"], x))


def vfl_spec(
    feature_splits: Sequence[int],
    hidden_dim: int,
    out_dim: int,
    lr: float,
    momentum: float = 0.9,
) -> dict:
    return {
        "feature_splits": tuple(int(d) for d in feature_splits),
        "hidden_dim": int(hidden_dim),
        "out_dim": int(out_dim),
        "opt": {"lr": float(lr), "momentum": float(momentum)},
    }


def make_vfl_fused_step(
    feature_splits: Sequence[int],
    hidden_dim: int = 16,
    out_dim: int = 1,
    lr: float = 0.05,
    momentum: float = 0.9,
):
    """The fused multi-party step — ``(all_params, opt_state, xs, y) ->
    (all_params, opt_state, loss, correct)`` over the list of party
    params (party 0 is the label-holding guest)."""
    opt = optax.sgd(lr, momentum=momentum)

    def builder():
        def loss_fn(all_params, xs, y):
            total = sum(
                _vfl_contribution(hidden_dim, out_dim, i == 0, pp, x)
                for i, (pp, x) in enumerate(zip(all_params, xs))
            )
            logit = total.reshape(-1)
            loss = optax.sigmoid_binary_cross_entropy(logit, y).mean()
            correct = jnp.sum((logit > 0) == (y > 0.5))
            return loss, correct

        def step(all_params, opt_state, xs, y):
            (loss, correct), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(all_params, xs, y)
            updates, opt_state = opt.update(grads, opt_state, all_params)
            all_params = optax.apply_updates(all_params, updates)
            return all_params, opt_state, loss, correct

        return jax.jit(step)

    return get_program_cache().get_or_build(
        "vfl_fused_step",
        {"kind": "vfl_fused_step",
         **vfl_spec(feature_splits, hidden_dim, out_dim, lr, momentum)},
        builder,
    )


def make_vfl_party_forward(hidden_dim: int, out_dim: int, has_labels: bool):
    """One party's forward: ``(params, x) -> contrib`` — the logit
    contribution that crosses the wire (host_trainer.py:43-78)."""
    def builder():
        def forward(params, x):
            return _vfl_contribution(hidden_dim, out_dim, has_labels, params, x)

        return jax.jit(forward)

    return get_program_cache().get_or_build(
        "vfl_party_forward",
        {"kind": "vfl_party_forward", "hidden_dim": int(hidden_dim),
         "out_dim": int(out_dim), "has_labels": bool(has_labels)},
        builder,
    )


def make_vfl_guest_grad(n_parties: int, out_dim: int = 1):
    """Guest side of the cut: ``(contribs, y) -> (loss, correct,
    contrib_grads)`` — the loss over the summed contributions plus
    dL/dh_k for every party (guest_trainer.py:96-126)."""
    def builder():
        def guest_grad(contribs, y):
            def guest_loss(all_c):
                logit = sum(all_c).reshape(-1)
                loss = optax.sigmoid_binary_cross_entropy(logit, y).mean()
                correct = jnp.sum((logit > 0) == (y > 0.5))
                return loss, correct

            (loss, correct), g = jax.value_and_grad(
                guest_loss, has_aux=True
            )(list(contribs))
            return loss, correct, g

        return jax.jit(guest_grad)

    return get_program_cache().get_or_build(
        "vfl_guest_grad",
        {"kind": "vfl_guest_grad", "parties": int(n_parties),
         "out_dim": int(out_dim)},
        builder,
    )


def make_vfl_party_update(
    hidden_dim: int,
    out_dim: int,
    has_labels: bool,
    lr: float = 0.05,
    momentum: float = 0.9,
):
    """One party's backward + local update with the returned contribution
    grads: ``(params, opt_state, x, contrib_grad) -> (params,
    opt_state)``."""
    opt = optax.sgd(lr, momentum=momentum)

    def builder():
        def step(params, opt_state, x, contrib_grad):
            _, vjp = jax.vjp(
                lambda q: _vfl_contribution(
                    hidden_dim, out_dim, has_labels, q, x
                ),
                params,
            )
            (grads,) = vjp(contrib_grad)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state

        return jax.jit(step)

    return get_program_cache().get_or_build(
        "vfl_party_update",
        {"kind": "vfl_party_update", "hidden_dim": int(hidden_dim),
         "out_dim": int(out_dim), "has_labels": bool(has_labels),
         "opt": {"lr": float(lr), "momentum": float(momentum)}},
        builder,
    )


def split_party_opt_states(opt, fused_state, all_params):
    """Fused sgd state over ``[p_0, ..., p_K]`` -> per-party states (the
    list pytree flattens party-contiguously, exactly like the SplitNN
    group split)."""
    leaves = jax.tree_util.tree_leaves(fused_state)
    out, i = [], 0
    for pp in all_params:
        t = _group_template(opt, pp)
        n = len(jax.tree_util.tree_leaves(t))
        out.append(
            jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(t), leaves[i : i + n]
            )
        )
        i += n
    return out


def merge_party_opt_states(opt, states, all_params):
    """Per-party states -> the fused sgd state over the param list — the
    inverse of :func:`split_party_opt_states`."""
    fused_t = _group_template(opt, list(all_params))
    leaves = [
        leaf for st in states for leaf in jax.tree_util.tree_leaves(st)
    ]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(fused_t), leaves
    )
