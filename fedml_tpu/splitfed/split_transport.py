"""Distributed split learning over the Message/Observer transport (ref:
fedml_api/distributed/split_nn/{SplitNNAPI.py, client.py, server.py,
message_define.py}).

The server owns the top half and the round FSM; clients own the bottom
half and their local shards. Per relay turn (one active client at a
time, ref client.py:12-13 ring neighbors):

1. server → client ``S2C_SPLIT_TURN``: the shared bottom params + bottom
   optimizer state — the relay hand-off that the reference implements as
   client→client weight passing, centralized here so the scheduler's
   SelectionPolicy (not a hardcoded neighbor list) decides the ring
   order and so a dead client can be skipped without re-wiring the ring;
2. per batch, client → server ``C2S_SPLIT_ACTS`` (cut-layer activations,
   optionally int8/int4-quantized — :mod:`fedml_tpu.splitfed.codec`) and
   server → client ``S2C_SPLIT_GRADS`` (∂L/∂acts, ref server.py:40-60
   ``acts.grad``) while the server updates its top half;
3. client → server ``C2S_SPLIT_DONE``: the updated bottom params + opt
   state (or a ``skipped`` decline when the fault plan crashed/dropped
   the turn — the ring advances instead of hanging on batches that will
   never come; that decline IS the deterministic-recovery contract, and
   it differs on purpose from the horizontal family's silent crash,
   which a quorum deadline absorbs there but nothing would absorb here).

All numerics run through the digested ProgramCache factories in
:mod:`fedml_tpu.splitfed.programs`; the composition over the wire is
bit-identical to the fused :class:`SplitNNAPI` simulator step
(tests/test_splitfed.py pins ``assert_array_equal``). Retries, comm
metering, wire-trace propagation, and flight-recorder phases
(``forward``/``boundary``/``backward``) all arrive through the standard
``BaseCommManager``/tracer wiring points."""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import RunConfig
from fedml_tpu.core.comm import BaseCommManager
from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
from fedml_tpu.core.managers import ClientManager, ServerManager
from fedml_tpu.core.message import Message, MessageType as MT
from fedml_tpu.core import compression as CZ
from fedml_tpu.models import ModelDef
from fedml_tpu.splitfed.codec import BOUNDARY_CODECS, ActivationCodec
from fedml_tpu.splitfed.programs import (
    make_split_optimizer,
    make_splitnn_client_backward,
    make_splitnn_client_forward,
    make_splitnn_eval,
    make_splitnn_server_step,
    merge_opt_state,
    split_opt_state,
)
from fedml_tpu.telemetry import (
    ClientHealthRegistry,
    get_comm_meter,
    get_tracer,
    wrap_in_current_scope,
)


def _host_tree(tree):
    return jax.tree_util.tree_map(
        lambda a: np.ascontiguousarray(np.asarray(a)), tree
    )


def _tree_bytes(tree) -> int:
    return 4 * sum(int(np.size(a)) for a in jax.tree_util.tree_leaves(tree))


def _opt_leaves(state) -> list:
    """Optimizer state as a flat leaf list — the wire representation
    (FTM1 params carry dict/list pytrees, not optax namedtuples); the
    receiver re-brackets against its local eval_shape template."""
    return [
        np.ascontiguousarray(np.asarray(leaf))
        for leaf in jax.tree_util.tree_leaves(state)
    ]


def _opt_unflatten(opt, params, leaves):
    template = jax.eval_shape(opt.init, params)
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template), list(leaves)
    )


class SplitNNServerManager(ServerManager):
    """Top-half owner + relay-ring FSM (ref server.py + SplitNNAPI.py
    run loop). Rank 0."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        bottom: ModelDef,
        top: ModelDef,
        data=None,
        worker_num: Optional[int] = None,
        log_fn=None,
        faults=None,
    ):
        super().__init__(comm, rank=0, config=config)
        self.config = config
        self.bottom = bottom
        self.top = top
        self.data = data
        self.log_fn = log_fn or (lambda m: None)
        self.worker_num = worker_num or config.fed.client_num_per_round
        ac = getattr(config.comm, "activation_compression", "none")
        if ac not in BOUNDARY_CODECS:
            raise ValueError(
                f"activation_compression supports {BOUNDARY_CODECS}; got {ac!r}"
            )
        self.faults = faults
        lr, mom, wd = config.train.lr, config.train.momentum, config.train.wd
        # the two halves init exactly like the fused simulator
        # (SplitNNAPI.__init__) so sim and transport start bit-identical
        k1, k2 = jax.random.split(jax.random.PRNGKey(config.seed))
        self._bottom_params = jax.device_get(bottom.init(k1))["params"]
        self._top_params = jax.device_get(top.init(k2))["params"]
        self._opt = make_split_optimizer(lr, mom, wd)
        self._server_optimizer = self._opt  # session checkpoint contract
        self._bottom_opt_state = self._opt.init(self._bottom_params)
        self._top_opt_state = self._opt.init(self._top_params)
        self._server_step = make_splitnn_server_step(top, lr, mom, wd)
        self._eval = make_splitnn_eval(bottom, top) if data is not None else None
        self._codec = ActivationCodec.from_config(config.comm)
        # round/turn FSM state — handlers run on the comm receive thread;
        # the lock serializes round completion against request_stop
        self.round_idx = 0
        self.history: List[dict] = []
        self._round_lock = threading.Lock()
        self._stop_requested = False
        self._federation_done = False
        self._dead_workers: set = set()
        self._cohort: List[int] = []
        self._turn_pos = 0
        self._next_batch = 0
        self._done_seen: set = set()
        self._loss_sum = 0.0
        self._batches = 0
        self.skipped_turns = 0
        self.dropped_boundary = 0  # stale/duplicate boundary msgs discarded
        self._round_span = None
        self._tracer = get_tracer()
        self.health = ClientHealthRegistry.from_config(config).attach(self._tracer)
        from fedml_tpu.scheduler import ClientScheduler

        # the SAME policy driver the horizontal family uses — the ring
        # order IS the selected cohort's order, so ring selection inherits
        # every registered SelectionPolicy (and the restore-time memo)
        self.scheduler = ClientScheduler.from_config(
            config,
            num_clients=config.fed.client_num_in_total,
            data=data,
            log_fn=self.log_fn,
            health=self.health,
            tracer=self._tracer,
        )

    # -- session/checkpoint surface (serve/session.py speaks this exact
    #    dialect to every sync server family) --
    @property
    def global_vars(self) -> dict:
        return {"params": {"bottom": self._bottom_params, "top": self._top_params}}

    @global_vars.setter
    def global_vars(self, tree: dict) -> None:
        # checkpoint-restore surface: runs before the serve loop starts,
        # but the halves it swaps are relay state everywhere else — take
        # the (free) lock rather than reason about restore timing per-site
        with self._round_lock:
            self._bottom_params = tree["params"]["bottom"]
            self._top_params = tree["params"]["top"]

    @property
    def _server_opt_state(self):
        """Both halves' optimizer states as ONE fused tree over the joint
        param dict — a split checkpoint row looks exactly like a
        horizontal one (programs.merge_opt_state is the exact inverse of
        the per-group split)."""
        return merge_opt_state(
            self._opt,
            self._bottom_opt_state,
            self._top_opt_state,
            self._bottom_params,
            self._top_params,
        )

    @_server_opt_state.setter
    def _server_opt_state(self, fused_state) -> None:
        self._bottom_opt_state, self._top_opt_state = split_opt_state(
            self._opt, fused_state, self._bottom_params, self._top_params
        )

    def finish(self):
        self.health.detach()
        super().finish()

    def request_stop(self, drain: bool = True) -> None:
        """Graceful per-tenant stop (fedml_tpu/serve/): drain lets the
        open round's relay finish; drain=False closes the round now with
        the turns already completed (the active turn's in-flight boundary
        messages round-tag-drop harmlessly)."""
        self._stop_requested = True
        if drain:
            return
        with self._round_lock:
            if not self._federation_done:
                self._complete_round()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MT.C2S_SPLIT_ACTS, self._on_acts)
        self.register_message_receive_handler(MT.C2S_SPLIT_DONE, self._on_done)

    def send_init_msg(self):
        self._t0 = time.monotonic()
        # steady-state rounds start under _round_lock (handler-driven via
        # _finish_or_next_round); the opening round must too, or its FSM
        # resets race the first activations arriving on the comm thread
        with self._round_lock:
            self._start_round()

    def _broadcast(self, msg: Message) -> bool:
        """Dead-peer-tolerant send (same contract as the FedAvg server's):
        a crashed client process must not take the ring FSM down — its
        turn is skipped and the relay advances."""
        worker = msg.get_receiver_id()
        if worker in self._dead_workers:
            return False
        try:
            self.send_message(msg)
            return True
        except Exception as e:  # noqa: BLE001 — transport errors vary by backend
            self._dead_workers.add(worker)
            logging.warning(
                "split turn send to worker %d failed (%s) — skipping turn",
                worker,
                e,
            )
            return False

    def _start_round(self):
        r = self.round_idx
        self._cohort = list(self.scheduler.select(r, k=self.worker_num))
        self._turn_pos = 0
        self._next_batch = 0
        self._loss_sum = 0.0
        self._batches = 0
        self._round_span = self._tracer.start_span("round", round=r)
        self._send_turn()

    def _send_turn(self):
        """Hand the relay baton (bottom params + bottom opt state) to the
        next live client in the ring; a failed hand-off skips the turn."""
        r = self.round_idx
        while self._turn_pos < len(self._cohort):
            worker = self._turn_pos + 1
            msg = Message(MT.S2C_SPLIT_TURN, 0, worker)
            msg.add_params(MT.ARG_MODEL_PARAMS, _host_tree(self._bottom_params))
            msg.add_params(MT.ARG_OPT_STATE, _opt_leaves(self._bottom_opt_state))
            msg.add_params(MT.ARG_CLIENT_INDEX, int(self._cohort[self._turn_pos]))
            msg.add_params(MT.ARG_ROUND_IDX, r)
            self._next_batch = 0
            with self._tracer.span("broadcast", round=r):
                sent = self._broadcast(msg)
            if sent:
                return
            self.skipped_turns += 1
            self._turn_pos += 1
        self._finish_or_next_round()

    def _turn_is_current(self, msg: Message) -> bool:
        return (
            not self._federation_done
            and msg.get(MT.ARG_ROUND_IDX) == self.round_idx
            and msg.get_sender_id() == self._turn_pos + 1
        )

    def _on_acts(self, msg: Message):
        # the whole boundary step runs under _round_lock: request_stop's
        # drain=False path completes the round from another thread, and
        # the FSM counters it resets are the ones mutated here
        with self._round_lock:
            self._on_acts_locked(msg)

    def _on_acts_locked(self, msg: Message):
        if not self._turn_is_current(msg) or int(msg.get(MT.ARG_BATCH_IDX)) != self._next_batch:
            self.dropped_boundary += 1
            return
        r = self.round_idx
        worker = msg.get_sender_id()
        payload = msg.get(MT.ARG_ACT_PAYLOAD)
        if payload is not None:
            acts = ActivationCodec.decode(payload, msg.get(MT.ARG_ACT_CODEC))
        else:
            acts = msg.get(MT.ARG_ACTIVATIONS)
        y = msg.get(MT.ARG_BATCH_LABELS)
        with self._tracer.span("boundary", round=r):
            (
                self._top_params,
                self._top_opt_state,
                loss,
                _correct,
                acts_grad,
            ) = self._server_step(
                self._top_params,
                self._top_opt_state,
                jnp.asarray(acts),
                jnp.asarray(y),
            )
        self._loss_sum += float(loss)
        self._batches += 1
        g = np.ascontiguousarray(np.asarray(acts_grad))
        out = Message(MT.S2C_SPLIT_GRADS, 0, worker)
        out.add_params(MT.ARG_ROUND_IDX, r)
        out.add_params(MT.ARG_BATCH_IDX, int(msg.get(MT.ARG_BATCH_IDX)))
        if self._codec is not None:
            gp = self._codec.encode(f"down:{worker}", g)
            get_comm_meter().on_downlink(CZ.payload_bytes(gp), g.nbytes)
            out.add_params(MT.ARG_ACT_PAYLOAD, gp)
            out.add_params(MT.ARG_ACT_CODEC, self._codec.method)
        else:
            get_comm_meter().on_downlink(g.nbytes, g.nbytes)
            out.add_params(MT.ARG_ACT_GRADS, g)
        self._next_batch += 1
        if not self._broadcast(out):
            # client died mid-turn: its bottom updates are lost with it —
            # the turn is abandoned and the PREVIOUS bottom state relays on
            self.skipped_turns += 1
            self._turn_pos += 1
            self._send_turn()

    def _on_done(self, msg: Message):
        with self._round_lock:
            self._on_done_locked(msg)

    def _on_done_locked(self, msg: Message):
        if not self._turn_is_current(msg):
            self.dropped_boundary += 1
            return
        key = (self.round_idx, msg.get_sender_id())
        if key in self._done_seen:  # flaky at-least-once duplicate
            self.dropped_boundary += 1
            return
        self._done_seen.add(key)
        if msg.get(MT.ARG_SKIPPED):
            # fault-plan decline: the bottom state relays on unchanged
            self.skipped_turns += 1
        else:
            self._bottom_params = msg.get(MT.ARG_MODEL_PARAMS)
            self._bottom_opt_state = _opt_unflatten(
                self._opt, self._bottom_params, msg.get(MT.ARG_OPT_STATE)
            )
        self._turn_pos += 1
        if self._turn_pos < len(self._cohort):
            self._send_turn()
        else:
            self._finish_or_next_round()

    def _finish_or_next_round(self):
        """Caller holds ``_round_lock`` (handlers enter through their
        locked wrappers; _start_round's callers hold it too)."""
        if self._federation_done:
            return
        self._complete_round()

    def _complete_round(self):
        """Close the open round: log the row, advance or FINISH. Caller
        holds ``_round_lock`` (or is the drain path, which takes it)."""
        r = self.round_idx
        row = {
            "round": r,
            "t_s": round(time.monotonic() - getattr(self, "_t0", time.monotonic()), 3),
            "Train/Loss": self._loss_sum / max(self._batches, 1),
            "split/skipped_turns": self.skipped_turns,
        }
        if self._eval is not None:
            with self._tracer.span("eval", round=r):
                x, y = self.data.test_x, self.data.test_y
                correct = 0
                for s in range(0, len(y), 128):
                    correct += int(
                        self._eval(
                            self._bottom_params,
                            self._top_params,
                            jnp.asarray(x[s : s + 128]),
                            jnp.asarray(y[s : s + 128]),
                        )
                    )
                row["Test/Acc"] = correct / max(len(y), 1)
        self.history.append(row)
        self.log_fn(row)
        if self._round_span is not None:
            self._round_span.end()
            self._round_span = None
        self.round_idx = r + 1
        if self.round_idx >= self.config.fed.comm_round or self._stop_requested:
            self._federation_done = True
            for worker in range(1, self.worker_num + 1):
                self._broadcast(Message(MT.FINISH, 0, worker))
            self.finish()
        else:
            self._start_round()


class SplitNNClientManager(ClientManager):
    """Bottom-half owner for one worker slot (ref client.py:24-34 forward/
    backward). Holds the full dataset handle; the turn message names which
    client's shard this slot plays this round (the sampler re-assigns
    clients to slots round by round, like the horizontal family)."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        rank: int,
        bottom: ModelDef,
        data,
        faults=None,
    ):
        super().__init__(comm, rank, config=config)
        self.config = config
        self.data = data
        self._faults = faults
        lr, mom, wd = config.train.lr, config.train.momentum, config.train.wd
        self._opt = make_split_optimizer(lr, mom, wd)
        self._forward = make_splitnn_client_forward(bottom)
        self._backward = make_splitnn_client_backward(bottom, lr, mom, wd)
        self._codec = ActivationCodec.from_config(config.comm)
        self._tracer = get_tracer()
        self._turn: Optional[Dict] = None

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MT.S2C_SPLIT_TURN, self._on_turn)
        self.register_message_receive_handler(MT.S2C_SPLIT_GRADS, self._on_grads)
        self.register_message_receive_handler(MT.FINISH, lambda m: self.finish())

    def _on_turn(self, msg: Message):
        self._turn = None  # a turn abandoned by the server leaves no state
        r = int(msg.get(MT.ARG_ROUND_IDX))
        cid = int(msg.get(MT.ARG_CLIENT_INDEX))
        params = msg.get(MT.ARG_MODEL_PARAMS)
        opt_state = _opt_unflatten(self._opt, params, msg.get(MT.ARG_OPT_STATE))
        fd = self._faults.decide(cid, r) if self._faults is not None else None
        if fd is not None and (fd.crashed or fd.drop):
            # decline the turn instead of going silent: the ring has no
            # quorum deadline to absorb silence, so the deterministic
            # recovery is an explicit skip — the server relays the
            # unchanged bottom state to the next client
            self._faults.record(cid, r, "crash" if fd.crashed else "dropout")
            self._send_done(r, cid, skipped=True)
            return
        if fd is not None and fd.slowdown_s:
            self._faults.record(cid, r, "slowdown", detail=fd.slowdown_s)
            time.sleep(fd.slowdown_s)
        x, y = self.data.client_x[cid], self.data.client_y[cid]
        bs = int(self.config.data.batch_size)
        n = len(y)
        # identical batch walk to SplitNNAPI.train_ring (drop-partial, no
        # shuffle, epochs_per_client epochs) — the parity contract
        starts = [
            s
            for _ in range(int(self.config.fed.epochs))
            for s in range(0, n - bs + 1, bs)
        ]
        self._turn = {
            "round": r,
            "cid": cid,
            "params": params,
            "opt_state": opt_state,
            "starts": starts,
            "pos": 0,
            "flaky": bool(fd.flaky) if fd is not None else False,
            "x": x,
            "y": y,
            "bs": bs,
            "xb": None,
        }
        if not starts:
            self._send_done(r, cid, skipped=False)
            return
        self._send_acts()

    def _send_acts(self):
        t = self._turn
        r, pos, bs = t["round"], t["pos"], t["bs"]
        s = t["starts"][pos]
        xb = jnp.asarray(t["x"][s : s + bs])
        t["xb"] = xb
        with self._tracer.span("forward", round=r):
            acts = np.ascontiguousarray(np.asarray(self._forward(t["params"], xb)))
        out = Message(MT.C2S_SPLIT_ACTS, self.rank, 0)
        if self._codec is not None:
            payload = self._codec.encode(f"up:{self.rank}", acts)
            get_comm_meter().on_uplink(CZ.payload_bytes(payload), acts.nbytes)
            out.add_params(MT.ARG_ACT_PAYLOAD, payload)
            out.add_params(MT.ARG_ACT_CODEC, self._codec.method)
        else:
            get_comm_meter().on_uplink(acts.nbytes, acts.nbytes)
            out.add_params(MT.ARG_ACTIVATIONS, acts)
        out.add_params(MT.ARG_BATCH_LABELS, np.asarray(t["y"][s : s + bs]))
        out.add_params(MT.ARG_BATCH_IDX, pos)
        out.add_params(MT.ARG_ROUND_IDX, r)
        out.add_params(MT.ARG_CLIENT_INDEX, t["cid"])
        self.send_message(out)

    def _on_grads(self, msg: Message):
        t = self._turn
        if (
            t is None
            or int(msg.get(MT.ARG_ROUND_IDX)) != t["round"]
            or int(msg.get(MT.ARG_BATCH_IDX)) != t["pos"]
        ):
            return  # stale round or duplicate batch reply
        payload = msg.get(MT.ARG_ACT_PAYLOAD)
        if payload is not None:
            g = ActivationCodec.decode(payload, msg.get(MT.ARG_ACT_CODEC))
        else:
            g = msg.get(MT.ARG_ACT_GRADS)
        with self._tracer.span("backward", round=t["round"]):
            t["params"], t["opt_state"] = self._backward(
                t["params"], t["opt_state"], t["xb"], jnp.asarray(g)
            )
        t["pos"] += 1
        if t["pos"] < len(t["starts"]):
            self._send_acts()
        else:
            self._send_done(t["round"], t["cid"], skipped=False)

    def _send_done(self, r: int, cid: int, skipped: bool):
        out = Message(MT.C2S_SPLIT_DONE, self.rank, 0)
        out.add_params(MT.ARG_ROUND_IDX, r)
        out.add_params(MT.ARG_CLIENT_INDEX, cid)
        if skipped:
            out.add_params(MT.ARG_SKIPPED, True)
        else:
            t = self._turn
            out.add_params(MT.ARG_MODEL_PARAMS, _host_tree(t["params"]))
            out.add_params(MT.ARG_OPT_STATE, _opt_leaves(t["opt_state"]))
        flaky = self._turn is not None and self._turn.get("flaky")
        self._turn = None
        self.send_message(out)
        if flaky:
            # flaky = at-least-once double delivery; the server's
            # (round, worker) done-dedupe absorbs the duplicate
            self._faults.record(cid, r, "flaky")
            try:
                self.send_message(out)
            except Exception:  # noqa: BLE001 — best-effort duplicate
                pass


def run_loopback_splitnn(
    config: RunConfig,
    data,
    bottom: Optional[ModelDef] = None,
    top: Optional[ModelDef] = None,
    log_fn=None,
    faults=None,
):
    """One-process split federation over the loopback hub: 1 server +
    worker_num client actors in threads. Returns the server manager
    (global_vars / history / skipped_turns)."""
    if bottom is None or top is None:
        from fedml_tpu.algorithms.split_nn import default_split_models

        bottom, top = default_split_models(
            tuple(data.client_x[0].shape[1:]), data.num_classes
        )
    hub = LoopbackHub()
    k = config.fed.client_num_per_round
    server = SplitNNServerManager(
        config,
        LoopbackCommManager(hub, 0),
        bottom,
        top,
        data=data,
        worker_num=k,
        log_fn=log_fn,
        faults=faults,
    )
    clients = [
        SplitNNClientManager(
            config, LoopbackCommManager(hub, rank), rank, bottom, data,
            faults=faults,
        )
        for rank in range(1, k + 1)
    ]
    threads = [
        # bind the spawner's telemetry scope to each client thread — bare
        # c.run would emit this tenant's spans into the global registry
        threading.Thread(
            target=wrap_in_current_scope(c.run), daemon=True,
            name=f"splitnn-client-{c.rank}",
        )
        for c in clients
    ]
    for t in threads:
        t.start()
    server.send_init_msg()
    server.run()
    for t in threads:
        t.join(timeout=60)
    return server
