from fedml_tpu.partition.noniid import (
    homo_partition,
    lda_partition,
    partition_class_samples_with_dirichlet,
    record_data_stats,
)

__all__ = [
    "homo_partition",
    "lda_partition",
    "partition_class_samples_with_dirichlet",
    "record_data_stats",
]
