"""Topology managers for decentralized FL — weighted digraphs of workers
(ref: fedml_core/distributed/topology/{base_topology_manager.py:4-23,
symmetric_topology_manager.py:21-53, asymmetric_topology_manager.py:7-70}).

Same construction: Watts-Strogatz(k, β=0) ring lattices merged with a base
ring, self-loops on the diagonal, rows normalized to a confusion (mixing)
matrix. On TPU this matrix IS the communication pattern: decentralized
gossip is `new_params = W @ stacked_params` over the client axis — a dense
(or ppermute-sparse) mixing step instead of per-edge messages
(SURVEY §2g "decentralized/gossip")."""

from __future__ import annotations

import abc
from typing import List

import numpy as np


def _ws_adjacency(n: int, k: int) -> np.ndarray:
    """Watts-Strogatz(β=0) ring-lattice adjacency without networkx: node i
    connects to the k//2 nearest neighbors on each side (matches
    nx.watts_strogatz_graph(n, k, 0))."""
    a = np.zeros((n, n), np.float32)
    half = max(1, k // 2)
    for d in range(1, half + 1):
        for i in range(n):
            a[i, (i + d) % n] = 1.0
            a[i, (i - d) % n] = 1.0
    return a


class BaseTopologyManager(abc.ABC):
    topology: np.ndarray

    @abc.abstractmethod
    def generate_topology(self) -> None: ...

    def get_in_neighbor_weights(self, node_index: int):
        return self.topology[:, node_index]

    def get_out_neighbor_weights(self, node_index: int):
        return self.topology[node_index]

    def get_in_neighbor_idx_list(self, node_index: int) -> List[int]:
        return [
            j
            for j, w in enumerate(self.topology[:, node_index])
            if w > 0 and j != node_index
        ]

    def get_out_neighbor_idx_list(self, node_index: int) -> List[int]:
        return [
            j
            for j, w in enumerate(self.topology[node_index])
            if w > 0 and j != node_index
        ]


class SymmetricTopologyManager(BaseTopologyManager):
    """Ring ∪ WS(neighbor_num) with self-loops, row-normalized
    (ref symmetric_topology_manager.py:21-53). Symmetric ⇒ doubly-stochastic
    mixing when degrees are equal."""

    def __init__(self, n: int, neighbor_num: int = 2):
        self.n = n
        self.neighbor_num = neighbor_num
        self.topology = np.zeros((n, n), np.float32)

    def generate_topology(self) -> None:
        t = np.maximum(
            _ws_adjacency(self.n, 2), _ws_adjacency(self.n, self.neighbor_num)
        )
        np.fill_diagonal(t, 1.0)
        self.topology = t / t.sum(axis=1, keepdims=True)


class AsymmetricTopologyManager(BaseTopologyManager):
    """Symmetric base plus randomly added directed links, row-normalized
    (ref asymmetric_topology_manager.py:24-70; the reference's np.random
    link flips are reproduced with a seeded Generator)."""

    def __init__(self, n: int, undirected_neighbor_num: int = 3, out_directed_neighbor: int = 3, seed: int = 0):
        self.n = n
        self.undirected_neighbor_num = undirected_neighbor_num
        self.out_directed_neighbor = out_directed_neighbor
        self.seed = seed
        self.topology = np.zeros((n, n), np.float32)

    def generate_topology(self) -> None:
        rng = np.random.default_rng(self.seed)
        t = np.maximum(
            _ws_adjacency(self.n, 2),
            _ws_adjacency(self.n, self.undirected_neighbor_num),
        )
        np.fill_diagonal(t, 1.0)
        out_links = set()
        for i in range(self.n):
            zeros = [j for j in range(self.n) if t[i, j] == 0]
            flips = rng.integers(0, 2, size=len(zeros))
            for j, f in zip(zeros, flips):
                if f == 1 and (j * self.n + i) not in out_links:
                    t[i, j] = 1.0
                    out_links.add(i * self.n + j)
        self.topology = t / t.sum(axis=1, keepdims=True)
