"""Non-IID data partitioners (pure numpy, host-side).

Behavioral parity with the reference's LDA/Dirichlet label-skew partitioner
(fedml_core/non_iid_partition/noniid_partition.py:6-102): each class's sample
indices are split across clients by a Dirichlet(alpha) draw, with a retry loop
guaranteeing every client at least ``min_size`` samples. Written fresh; the
capacity-capping trick (clients already at fair share receive no more of a
class) matches the reference's proportion-zeroing behavior.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def homo_partition(n_samples: int, num_clients: int, rng: np.random.Generator) -> Dict[int, np.ndarray]:
    """IID partition: shuffle and split evenly (ref base.py:181-184 'homo')."""
    idxs = rng.permutation(n_samples)
    return {i: np.sort(part) for i, part in enumerate(np.array_split(idxs, num_clients))}


def partition_class_samples_with_dirichlet(
    rng: np.random.Generator,
    alpha: float,
    client_idx_batches: List[List[int]],
    class_idxs: np.ndarray,
    n_total: int,
    num_clients: int,
) -> List[List[int]]:
    """Split one class's indices across clients by a capped Dirichlet draw
    (ref noniid_partition.py:76-92)."""
    rng.shuffle(class_idxs)
    proportions = rng.dirichlet(np.repeat(alpha, num_clients))
    # Cap: clients that already hold a fair share get none of this class.
    fair = n_total / num_clients
    proportions = np.array(
        [p * (len(batch) < fair) for p, batch in zip(proportions, client_idx_batches)]
    )
    proportions = proportions / proportions.sum()
    cuts = (np.cumsum(proportions) * len(class_idxs)).astype(int)[:-1]
    return [
        batch + split.tolist()
        for batch, split in zip(client_idx_batches, np.split(class_idxs, cuts))
    ]


def lda_partition(
    labels: np.ndarray,
    num_clients: int,
    alpha: float,
    seed: int = 0,
    min_size: int = 10,
) -> Dict[int, np.ndarray]:
    """LDA (Dirichlet) label-skew partition for classification
    (ref noniid_partition.py:6-73, retry loop at :44).

    Returns {client_id: sorted sample indices}.
    """
    labels = np.asarray(labels).reshape(-1)
    n_total = labels.shape[0]
    classes = np.unique(labels)
    rng = np.random.default_rng(seed)

    # The per-client minimum can never exceed the mean shard size, so the
    # reference's fixed ≥10 requirement (noniid_partition.py:44) is
    # unsatisfiable on small datasets and its retry loop would spin forever
    # — cap at the achievable value. A retry bound guards the remaining
    # (probabilistic) loop; at any feasible min_size it trips only if the
    # draw distribution makes the target astronomically unlikely.
    min_size = min(min_size, n_total // num_clients)
    current_min = -1
    batches: List[List[int]] = [[] for _ in range(num_clients)]
    for _ in range(10_000):
        batches = [[] for _ in range(num_clients)]
        for c in classes:
            class_idxs = np.where(labels == c)[0]
            batches = partition_class_samples_with_dirichlet(
                rng, alpha, batches, class_idxs, n_total, num_clients
            )
        current_min = min(len(b) for b in batches)
        if current_min >= min_size:
            break
    else:
        raise RuntimeError(
            f"LDA partition: could not reach min {min_size} samples/client "
            f"(n={n_total}, clients={num_clients}, alpha={alpha}) in 10k draws"
        )

    out: Dict[int, np.ndarray] = {}
    for i, batch in enumerate(batches):
        out[i] = np.sort(np.array(batch, dtype=np.int64))
    return out


def record_data_stats(labels: np.ndarray, net_dataidx_map: Dict[int, np.ndarray]) -> Dict[int, dict]:
    """Per-client class histogram (ref noniid_partition.py:94-102)."""
    stats = {}
    for client, idxs in net_dataidx_map.items():
        unq, counts = np.unique(np.asarray(labels)[idxs], return_counts=True)
        stats[client] = {int(u): int(c) for u, c in zip(unq, counts)}
    return stats
