"""local_test_on_all_clients (ref fedavg_api.py:117-180): pooled per-client
evaluation equals the reference's weighted per-client aggregate; ci flag
short-circuits to client 0; eval_on_clients wires it into the round loop."""

import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression

NUM_CLASSES = 3
FEAT = (5,)


def _data_with_client_tests():
    base = synthetic_classification(
        num_clients=5, num_classes=NUM_CLASSES, feat_shape=FEAT,
        samples_per_client=20, partition_method="homo", seed=2,
    )
    rng = np.random.default_rng(9)
    ctx = [
        rng.normal(size=(6 + i, *FEAT)).astype(np.float32) for i in range(5)
    ]
    cty = [
        rng.integers(0, NUM_CLASSES, size=(6 + i,)).astype(np.int32)
        for i in range(5)
    ]
    return FederatedDataset(
        name=base.name, client_x=base.client_x, client_y=base.client_y,
        test_x=base.test_x, test_y=base.test_y, num_classes=base.num_classes,
        client_test_x=ctx, client_test_y=cty,
    )


def _model():
    return ModelDef(
        LogisticRegression(num_classes=NUM_CLASSES), FEAT, NUM_CLASSES,
        name="lr",
    )


def _cfg(ci=False, eval_on_clients=False):
    return RunConfig(
        data=DataConfig(batch_size=16),
        fed=FedConfig(
            client_num_in_total=5, client_num_per_round=5, comm_round=2,
            frequency_of_the_test=1, ci=ci, eval_on_clients=eval_on_clients,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def _ref_weighted_aggregate(api, xs_list, ys_list):
    """Reference semantics: per-client sums, sample-weighted aggregate —
    identical to pooled sums."""
    from fedml_tpu.train.evaluate import pad_to_batches
    import jax.numpy as jnp

    tot_correct = tot_loss = tot_n = 0.0
    for x, y in zip(xs_list, ys_list):
        m = api.eval_fn(
            api.global_vars, *map(jnp.asarray, pad_to_batches(x, y, 16))
        )
        tot_correct += float(m["correct"])
        tot_loss += float(m["loss_sum"])
        tot_n += float(m["count"])
    return tot_loss / tot_n, tot_correct / tot_n


def test_matches_per_client_weighted_aggregate():
    data = _data_with_client_tests()
    api = FedAvgAPI(_cfg(), data, _model())
    row = api.local_test_on_all_clients(round_idx=0)
    ref_tr_loss, ref_tr_acc = _ref_weighted_aggregate(
        api, data.client_x, data.client_y
    )
    ref_te_loss, ref_te_acc = _ref_weighted_aggregate(
        api, data.client_test_x, data.client_test_y
    )
    assert row["Train/Acc"] == pytest.approx(ref_tr_acc, abs=1e-6)
    assert row["Train/Loss"] == pytest.approx(ref_tr_loss, rel=1e-5)
    assert row["Test/Acc"] == pytest.approx(ref_te_acc, abs=1e-6)
    assert row["Test/Loss"] == pytest.approx(ref_te_loss, rel=1e-5)


def test_ci_short_circuits_to_client_zero():
    data = _data_with_client_tests()
    api = FedAvgAPI(_cfg(ci=True), data, _model())
    row = api.local_test_on_all_clients()
    ref_loss, ref_acc = _ref_weighted_aggregate(
        api, data.client_x[:1], data.client_y[:1]
    )
    assert row["Train/Acc"] == pytest.approx(ref_acc, abs=1e-6)
    assert row["Train/Loss"] == pytest.approx(ref_loss, rel=1e-5)


def test_no_client_test_split_falls_back_to_central():
    data = synthetic_classification(
        num_clients=4, num_classes=NUM_CLASSES, feat_shape=FEAT,
        samples_per_client=12, partition_method="homo", seed=1,
    )
    api = FedAvgAPI(_cfg(), data, _model())
    row = api.local_test_on_all_clients()
    loss, acc = api.evaluate_global()
    assert row["Test/Acc"] == pytest.approx(acc, abs=1e-6)


def test_eval_on_clients_in_round_loop():
    data = _data_with_client_tests()
    api = FedAvgAPI(_cfg(eval_on_clients=True), data, _model())
    final = api.train()
    assert "Test/Acc" in final and "Train/Acc" in final
    # local eval overrode the cohort train metrics with all-client metrics
    row0 = api.history[0]
    check = api.local_test_on_all_clients()  # post-training model
    assert np.isfinite(row0["Train/Loss"]) and np.isfinite(check["Train/Loss"])
