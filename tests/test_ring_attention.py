"""Ring attention vs full attention — exact-equivalence oracle on the
virtual 8-device CPU mesh, plus the sequence-parallel LM train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.parallel.mesh import make_mesh
from fedml_tpu.parallel.ring_attention import (
    full_attention,
    make_ring_attention,
)

B, T, H, D = 2, 32, 4, 16  # T=32 over 8 shards -> T_local=4


def _qkv(seed):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.normal(size=(B, T, H, D)).astype(np.float32) * 0.5
    )
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(causal):
    mesh = make_mesh(8, axis_name="seq")
    ring = make_ring_attention(mesh, axis_name="seq", causal=causal)
    q, k, v = _qkv(0)
    out_ring = ring(q, k, v)
    out_full = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_full), atol=2e-5, rtol=2e-5
    )


def test_ring_mesh_size_invariance():
    """Same math on 2 shards and 8 shards."""
    q, k, v = _qkv(1)
    outs = []
    for n in (2, 8):
        mesh = make_mesh(n, axis_name="seq")
        ring = make_ring_attention(mesh, axis_name="seq", causal=True)
        outs.append(np.asarray(ring(q, k, v)))
    np.testing.assert_allclose(outs[0], outs[1], atol=2e-5, rtol=2e-5)


def test_sp_lm_train_step_learns():
    from fedml_tpu.parallel.long_context import make_sp_train_step

    mesh = make_mesh(8, axis_name="seq")
    V = 50
    init_fn, step = make_sp_train_step(
        mesh, V, lr=1e-2, num_layers=1, num_heads=2, embed_dim=32, max_len=T
    )
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    params, opt_state = init_fn(jax.random.PRNGKey(0), tokens)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9
    assert np.isfinite(losses).all()


def test_sp_lm_matches_single_device():
    """SP training step == unsharded step (same seeds, same data)."""
    import optax

    from fedml_tpu.models.transformer import TransformerLM
    from fedml_tpu.parallel.long_context import make_sp_train_step

    V = 31
    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    mesh = make_mesh(8, axis_name="seq")
    init_fn, step = make_sp_train_step(
        mesh, V, lr=1e-2, num_layers=1, num_heads=2, embed_dim=32, max_len=T
    )
    params, opt_state = init_fn(jax.random.PRNGKey(1), tokens)

    # unsharded reference with identical init
    model = TransformerLM(vocab_size=V, num_layers=1, num_heads=2, embed_dim=32, max_len=T)
    opt = optax.adamw(1e-2)
    ref_params = params
    ref_opt = opt.init(ref_params)

    def ref_loss(p):
        logits = model.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        rl, rg = jax.value_and_grad(ref_loss)(ref_params)
        updates, ref_opt = opt.update(rg, ref_opt, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
    np.testing.assert_allclose(float(loss), float(rl), atol=1e-4, rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(ref_params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)
