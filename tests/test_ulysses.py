"""Ulysses all-to-all sequence parallelism (parallel/ulysses.py): exactness
vs full attention on the 8-device mesh, and the SP LM train step under
sp_impl=ulysses matches sp_impl=ring (both are exact attention, so one
training step must agree to fp tolerance)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.parallel.ring_attention import full_attention, make_ring_attention
from fedml_tpu.parallel.ulysses import make_ulysses_attention


def _mesh(n=8):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


def _qkv(B, T, H, D, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention(causal):
    mesh = _mesh()
    B, T, H, D = 2, 64, 8, 16  # H divisible by 8 shards
    q, k, v = _qkv(B, T, H, D)
    spec = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = make_ulysses_attention(mesh, causal=causal)(qs, ks, vs)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ulysses_matches_ring():
    mesh = _mesh()
    q, k, v = _qkv(1, 64, 8, 16, seed=3)
    spec = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    u = make_ulysses_attention(mesh, causal=True)(qs, ks, vs)
    r = make_ring_attention(mesh, causal=True)(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r), atol=2e-5)


def test_ulysses_with_flash_core():
    """The Pallas flash kernel as the per-device attention core under
    ulysses (the long-context configuration: all-to-all reshard + blockwise
    local attention, no T×T materialisation anywhere)."""
    from fedml_tpu.ops import flash_attention_bthd

    mesh = _mesh()
    B, T, H, D = 1, 128, 8, 16
    q, k, v = _qkv(B, T, H, D, seed=5)
    spec = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = make_ulysses_attention(
        mesh,
        causal=True,
        attn_fn=lambda q, k, v, causal: flash_attention_bthd(
            q, k, v, causal=causal, block_q=64, block_k=64
        ),
    )(qs, ks, vs)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sp_train_step_ring_vs_ulysses():
    from fedml_tpu.parallel.long_context import make_sp_train_step

    mesh = _mesh()
    V, B, T = 64, 2, 64
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, V, size=(B, T)), jnp.int32
    )
    targets = jnp.roll(tokens, -1, axis=1)
    results = {}
    for impl in ("ring", "ulysses"):
        init_fn, step = make_sp_train_step(
            mesh, V, lr=1e-3, sp_impl=impl,
            num_layers=1, num_heads=8, embed_dim=32, max_len=T,
        )
        params, opt_state = init_fn(jax.random.PRNGKey(0), tokens)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        results[impl] = (params, float(loss))
    assert results["ring"][1] == pytest.approx(results["ulysses"][1], rel=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(results["ring"][0]),
        jax.tree_util.tree_leaves(results["ulysses"][0]),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )
