"""Federated transformer-LM fine-tuning (the FedNLP leg — the reference
ships only a pointer README, applications/FedNLP/README.md, and its in-repo
NLP ceiling is the 2-layer LSTM of model/nlp/rnn.py)."""

import numpy as np
import pytest

from fedml_tpu.config import (
    DataConfig,
    FedConfig,
    RunConfig,
    ServerConfig,
    TrainConfig,
)
from fedml_tpu.data.synthetic import synthetic_shakespeare
from fedml_tpu.models import create_model


def _setup(num_clients=8):
    data = synthetic_shakespeare(num_clients=num_clients, seed=0, seq_targets=True)
    model = create_model(
        "transformer", "shakespeare_synth", (80,), 90,
        num_layers=1, num_heads=2, embed_dim=32,
    )
    return data, model


def test_fedavg_transformer_nwp_learns():
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    data, model = _setup()
    cfg = RunConfig(
        data=DataConfig(batch_size=8, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=data.num_clients,
            client_num_per_round=4,
            comm_round=4,
            epochs=1,
            frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.5),
        model="transformer",
        seed=0,
    )
    api = FedAvgAPI(cfg, data, model, task="nwp")
    losses = []
    for r in range(cfg.fed.comm_round):
        _, m = api.train_round(r)
        losses.append(float(m["loss_sum"]) / max(float(m["count"]), 1))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # the LM is learning the Markov stream


def test_transformer_registry_rejects_moe():
    with pytest.raises(ValueError):
        create_model("transformer", "shakespeare", (80,), 90, moe_experts=4)


def test_fedopt_transformer_runs():
    """Server-optimizer family composes with the transformer unchanged."""
    from fedml_tpu.algorithms.fedopt import FedOptAPI

    data, model = _setup()
    cfg = RunConfig(
        data=DataConfig(batch_size=8, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=data.num_clients,
            client_num_per_round=4,
            comm_round=1,
            epochs=1,
            frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.5),
        server=ServerConfig(server_optimizer="adam", server_lr=0.01),
        model="transformer",
        seed=0,
    )
    api = FedOptAPI(cfg, data, model, task="nwp")
    _, m = api.train_round(0)
    assert np.isfinite(float(m["loss_sum"]))
