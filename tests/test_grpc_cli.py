"""True multi-process federation over gRPC through the CLI (ref
main_fedavg_rpc.py + run scripts: one OS process per participant). Spawns
rank 0 (server) + 2 client ranks as subprocesses on localhost and asserts
the server reports the final round."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest


@pytest.mark.slow
def test_multiprocess_grpc_federation(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single device per process is fine
    base = [
        sys.executable, "-m", "fedml_tpu",
        "--algorithm", "fedavg",
        "--runtime", "grpc",
        "--dataset", "synthetic",
        "--model", "lr",
        "--client_num_in_total", "2",
        "--client_num_per_round", "2",
        "--comm_round", "2",
        "--batch_size", "-1",
        "--frequency_of_the_test", "2",
        "--base_port", "9310",
        "--seed", "5",
    ]
    procs = [
        subprocess.Popen(
            base + ["--rank", str(rank)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for rank in (1, 2, 0)  # clients first, but any order works
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    server_out = outs[-1]
    last = [l for l in server_out.splitlines() if l.startswith("{")][-1]
    row = json.loads(last)
    assert row["round"] == 1  # rounds 0..1 completed
    assert "Test/Acc" in row


@pytest.mark.slow
def test_multiprocess_async_grpc_federation(tmp_path):
    """Barrier-free federation across real OS processes over gRPC:
    rank 0 runs the FedBuff server, ranks 1-2 train-on-arrival. The
    server must complete every buffered step and exit 0 — and the
    clients must exit 0 too, even when their LAST upload races the
    server's shutdown (the normal async end-of-run)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    base = [
        sys.executable, "-m", "fedml_tpu",
        "--algorithm", "fedbuff",
        "--runtime", "grpc",
        "--dataset", "synthetic",
        "--model", "lr",
        "--client_num_in_total", "6",
        "--client_num_per_round", "2",
        "--comm_round", "4",
        "--async_buffer_k", "2",
        "--batch_size", "8",
        "--base_port", "9350",
        "--seed", "5",
    ]
    procs = [
        subprocess.Popen(
            base + ["--rank", str(rank)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for rank in (1, 2, 0)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    server_out = outs[-1]
    last = [l for l in server_out.splitlines() if l.startswith("{")][-1]
    row = json.loads(last)
    assert row["server_step"] == 4
    assert "staleness_mean" in row


@pytest.mark.slow
def test_grpc_client_killed_mid_round_server_completes_on_quorum(tmp_path):
    """Chaos: one client process is SIGKILLed mid-federation (VERDICT r2
    Next #7). The server must absorb the dead peer (broadcast failures
    tolerated, deadline+quorum closes the round), keep training with the
    survivors, and exit 0 with the final round logged."""
    import signal
    import time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    base = [
        sys.executable, "-m", "fedml_tpu",
        "--algorithm", "fedavg",
        "--runtime", "grpc",
        "--dataset", "synthetic",
        "--model", "lr",
        "--client_num_in_total", "3",
        "--client_num_per_round", "3",
        "--comm_round", "4",
        "--batch_size", "-1",
        "--frequency_of_the_test", "4",
        "--deadline_s", "2.0",
        "--min_clients", "2",
        "--base_port", "9330",
        "--seed", "5",
    ]
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = {
        rank: subprocess.Popen(
            base + ["--rank", str(rank)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
            cwd=cwd,
        )
        for rank in (1, 2, 3, 0)
    }
    import threading

    lines = []
    reader = threading.Thread(
        target=lambda: lines.extend(iter(procs[0].stdout.readline, "")),
        daemon=True,
    )
    reader.start()
    try:
        # wait until round 0 has actually completed (first logged row) so
        # the kill lands mid-federation, not during process startup
        deadline = time.time() + 180
        while time.time() < deadline and not any(
            l.startswith("{") for l in lines
        ):
            assert procs[0].poll() is None, "".join(lines)[-2000:]
            time.sleep(0.5)
        assert any(l.startswith("{") for l in lines), "round 0 never completed"
        procs[3].send_signal(signal.SIGKILL)
        assert procs[0].wait(timeout=240) == 0, "".join(lines)[-2000:]
        reader.join(timeout=10)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
    rows = [json.loads(l) for l in lines if l.startswith("{")]
    assert rows and rows[-1]["round"] == 3  # rounds 0..3 completed
    assert "Test/Acc" in rows[-1]
    assert np.isfinite(rows[-1]["Test/Acc"])
