"""True multi-process federation over gRPC through the CLI (ref
main_fedavg_rpc.py + run scripts: one OS process per participant). Spawns
rank 0 (server) + 2 client ranks as subprocesses on localhost and asserts
the server reports the final round."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_multiprocess_grpc_federation(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single device per process is fine
    base = [
        sys.executable, "-m", "fedml_tpu",
        "--algorithm", "fedavg",
        "--runtime", "grpc",
        "--dataset", "synthetic",
        "--model", "lr",
        "--client_num_in_total", "2",
        "--client_num_per_round", "2",
        "--comm_round", "2",
        "--batch_size", "-1",
        "--frequency_of_the_test", "2",
        "--base_port", "9310",
        "--seed", "5",
    ]
    procs = [
        subprocess.Popen(
            base + ["--rank", str(rank)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for rank in (1, 2, 0)  # clients first, but any order works
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    server_out = outs[-1]
    last = [l for l in server_out.splitlines() if l.startswith("{")][-1]
    row = json.loads(last)
    assert row["round"] == 1  # rounds 0..1 completed
    assert "Test/Acc" in row
