"""Padding must be mathematically invisible (the core shape-contract claim of
fedml_tpu/data/base.py) — including for stateful optimizers (momentum/Adam)
and the FedProx prox term, where a padded step would otherwise still move
params via optimizer state. Regression test for the gated step in
train/client.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.config import TrainConfig
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.train.client import make_local_train


def _run(tc, n_real_steps, n_pad_steps, epochs=2):
    model = ModelDef(LogisticRegression(num_classes=3), (4,), 3)
    variables = model.init(jax.random.PRNGKey(0))
    B = 5
    rng = np.random.default_rng(0)
    S = n_real_steps + n_pad_steps
    x = np.zeros((S, B, 4), np.float32)
    y = np.zeros((S, B), np.int32)
    m = np.zeros((S, B), np.float32)
    x[:n_real_steps] = rng.normal(size=(n_real_steps, B, 4))
    y[:n_real_steps] = rng.integers(0, 3, size=(n_real_steps, B))
    m[:n_real_steps] = 1.0
    fn = make_local_train(model, tc, epochs=epochs, reshuffle_each_epoch=False)
    out_vars, metrics = jax.jit(fn)(
        variables, jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jax.random.PRNGKey(7)
    )
    return out_vars, metrics


@pytest.mark.parametrize(
    "tc",
    [
        TrainConfig(client_optimizer="sgd", lr=0.1, momentum=0.9),
        TrainConfig(client_optimizer="adam", lr=0.01),
        TrainConfig(client_optimizer="sgd", lr=0.1, prox_mu=0.1),
        TrainConfig(client_optimizer="sgd", lr=0.1, wd=0.01),
    ],
    ids=["momentum", "adam", "prox", "wd"],
)
def test_trailing_padding_is_noop(tc):
    v_unpadded, m_unpadded = _run(tc, n_real_steps=2, n_pad_steps=0)
    v_padded, m_padded = _run(tc, n_real_steps=2, n_pad_steps=3)
    for a, b in zip(
        jax.tree_util.tree_leaves(v_unpadded), jax.tree_util.tree_leaves(v_padded)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert float(m_unpadded["count"]) == float(m_padded["count"])


def test_registries():
    from fedml_tpu.config import RunConfig, DataConfig, FedConfig
    from fedml_tpu.data import load_dataset
    from fedml_tpu.models import create_model

    cfg = RunConfig(
        data=DataConfig(dataset="synthetic"), fed=FedConfig(client_num_in_total=4)
    )
    data = load_dataset(cfg)
    assert data.num_clients == 4
    model = create_model("lr", "synthetic", (28, 28, 1), 10)
    assert model.num_classes == 10
    cfg2 = cfg.replace(data=DataConfig(dataset="synthetic_1_1"))
    data2 = load_dataset(cfg2)
    assert data2.num_clients == 4
    with pytest.raises(KeyError):
        load_dataset(cfg.replace(data=DataConfig(dataset="nope")))
    with pytest.raises(KeyError):
        create_model("nope", "synthetic", (1,), 2)
