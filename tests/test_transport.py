"""Transport-layer tests: binary Message round-trip, loopback federation
(threaded server+clients) against the vmap simulator, and a localhost gRPC
echo. The reference has none of these (SURVEY §4: its comm 'tests' are
__main__ benchmark blocks, mqtt_comm_manager.py:131-150)."""

import threading

import numpy as np
import pytest

from fedml_tpu.core.message import Message, MessageType as MT


def test_message_binary_roundtrip():
    m = Message("test_type", sender_id=3, receiver_id=7)
    m.add_params("scalar", 42)
    m.add_params("text", "hello")
    m.add_params("flag", True)
    tree = {
        "layer1": {"w": np.arange(12, dtype=np.float32).reshape(3, 4), "b": np.zeros(4, np.float64)},
        "ints": np.array([1, 2, 3], np.int32),
    }
    m.add_params("params", tree)
    m.add_params("list_of_arrays", [np.ones(2, np.float32), np.full(3, 7, np.int64)])

    data = m.to_bytes()
    assert isinstance(data, bytes)
    out = Message.from_bytes(data)
    assert out.get_type() == "test_type"
    assert out.get_sender_id() == 3 and out.get_receiver_id() == 7
    assert out.get("scalar") == 42
    assert out.get("text") == "hello"
    assert out.get("flag") is True
    p = out.get("params")
    np.testing.assert_array_equal(p["layer1"]["w"], tree["layer1"]["w"])
    assert p["layer1"]["b"].dtype == np.float64  # dtype preserved, not JSON-listified
    np.testing.assert_array_equal(p["ints"], tree["ints"])
    la = out.get("list_of_arrays")
    np.testing.assert_array_equal(la[1], np.full(3, 7, np.int64))


def test_mqtt_federation_matches_simulator():
    """Same oracle as the loopback test, over the MQTT backend's embedded
    broker (ref mqtt topic scheme, mqtt_comm_manager.py:48-72,100-123) —
    the VERDICT r1 #5 contract: federation==simulator over MQTT."""
    import jax

    from fedml_tpu.algorithms import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_transport import run_mqtt_federation
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(5,), samples_per_client=12,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,), num_classes=3, name="lr"
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=3, epochs=1,
            frequency_of_the_test=3,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    sim = FedAvgAPI(cfg, data, model_def())
    sim.train()

    server = run_mqtt_federation(cfg, data, model_def())
    assert server.round_idx == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(server.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_mqtt_embedded_broker_pubsub():
    """Broker semantics: exact-topic fan-out, unsubscribe stops delivery."""
    import queue

    from fedml_tpu.core.mqtt_comm import EmbeddedBroker

    broker = EmbeddedBroker()
    q1, q2 = queue.Queue(), queue.Queue()
    broker.subscribe("fedml_tpu/to_1", q1)
    broker.subscribe("fedml_tpu/to_1", q2)
    broker.publish("fedml_tpu/to_1", b"hello")
    assert q1.get(timeout=1) == b"hello" and q2.get(timeout=1) == b"hello"
    broker.publish("fedml_tpu/to_2", b"other")  # nobody subscribed: dropped
    broker.unsubscribe("fedml_tpu/to_1", q2)
    broker.publish("fedml_tpu/to_1", b"again")
    assert q1.get(timeout=1) == b"again"
    assert q2.empty()


def test_mqtt_host_path_uses_builtin_client_without_paho():
    """Without paho, MqttCommManager(host=...) falls back to the built-in
    MQTT 3.1.1 client over a real TCP socket (core/mqtt_broker.py)."""
    from fedml_tpu.core.mqtt_broker import MiniMqttBroker
    from fedml_tpu.core.mqtt_comm import MqttCommManager
    from fedml_tpu.core.message import Message

    broker = MiniMqttBroker()
    try:
        a = MqttCommManager(1, host=broker.host, port=broker.port)
        b = MqttCommManager(2, host=broker.host, port=broker.port)
        import time

        time.sleep(0.1)  # let SUBSCRIBEs land before publishing (QoS 0)
        got = []
        b.add_observer(type("O", (), {"receive_message": lambda self, t, m: got.append(m)})())
        t = threading.Thread(target=b.handle_receive_message, daemon=True)
        t.start()
        m = Message("ping", 1, 2)
        m.add_params("x", np.arange(5).astype(np.int32))
        a.send_message(m)
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got and got[0].get_type() == "ping"
        np.testing.assert_array_equal(got[0].get("x"), np.arange(5))
        b.stop_receive_message()
        t.join(timeout=5)
        a.stop_receive_message()
    finally:
        broker.close()


def test_loopback_federation_matches_simulator():
    """Full-participation full-batch E=1: the transport path must equal the
    vmap simulator (which itself equals centralized — the reference's CI
    oracle, CI-script-fedavg.sh:42-48)."""
    import jax

    from fedml_tpu.algorithms import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(5,), samples_per_client=12,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,), num_classes=3, name="lr"
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=3, epochs=1,
            frequency_of_the_test=3,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    sim = FedAvgAPI(cfg, data, model_def())
    sim.train()

    server = run_loopback_federation(cfg, data, model_def())
    assert server.round_idx == 3
    assert "Test/Acc" in server.history[-1]
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(server.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_grpc_roundtrip():
    """Two managers on localhost ports exchange one binary message
    (ref gRPC backend process model, grpc_comm_manager.py:22-76)."""
    import queue

    from fedml_tpu.core.grpc_comm import GrpcCommManager
    from fedml_tpu.core.comm import Observer

    ip = {0: "127.0.0.1", 1: "127.0.0.1"}
    a = GrpcCommManager(0, ip, base_port=18890)
    b = GrpcCommManager(1, ip, base_port=18890)
    got = queue.Queue()

    class Sink(Observer):
        def receive_message(self, msg_type, msg):
            got.put((msg_type, msg))
            b.stop_receive_message()

    b.add_observer(Sink())
    m = Message("ping", 0, 1)
    m.add_params("payload", np.arange(5, dtype=np.float32))
    a.send_message(m)
    b.handle_receive_message()  # drains until stop
    msg_type, msg = got.get(timeout=5)
    assert msg_type == "ping"
    np.testing.assert_array_equal(msg.get("payload"), np.arange(5, dtype=np.float32))
    a.stop_receive_message()


def test_mqtt_socket_federation():
    """Federation over REAL TCP MQTT (VERDICT r2 Next #6): mini broker +
    built-in 3.1.1 client, full-participation LR run matches the vmap
    simulator to float tolerance."""
    import jax

    from fedml_tpu.algorithms import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_transport import run_federation
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.core.mqtt_broker import MiniMqttBroker
    from fedml_tpu.core.mqtt_comm import MqttCommManager
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(5,), samples_per_client=12,
        partition_method="homo", seed=3,
    )
    mk_model = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=3,
            epochs=1, frequency_of_the_test=3,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    broker = MiniMqttBroker()
    try:
        server = run_federation(
            cfg, data, mk_model(),
            comm_factory=lambda rank: MqttCommManager(
                rank, host=broker.host, port=broker.port
            ),
        )
    finally:
        broker.close()
    assert server.round_idx == 3
    sim = FedAvgAPI(cfg, data, mk_model())
    sim.train()
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(server.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
