"""Fused (custom-VJP) BatchNorm: exactness against flax nn.BatchNorm.

The op replaces AD-derived BN gradients with the hand-written full BN
backward and reconstructs the folded ReLU mask — these tests pin forward,
backward (dx, dgamma, dbeta — including the μ/σ² terms), running-stat
EMA updates, eval mode, and whole-model equivalence under the env A/B
switch, in fp32 and bf16.
"""

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models.norms import FusedBatchNorm
from fedml_tpu.ops.fused_batchnorm import bn_act, bn_inference

EPS = 1e-5


def _ref_bn(x, gamma, beta, relu):
    """Differentiable unfused reference (fp32 math, biased stats)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 1, 2))
    var = jnp.mean(x32 * x32, axis=(0, 1, 2)) - mean**2
    y = (x32 - mean) * jax.lax.rsqrt(var + EPS) * gamma + beta
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("relu", [False, True])
def test_bn_act_forward_and_grads_match_reference(dtype, relu):
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (4, 5, 5, 8), dtype)
    gamma = jax.random.normal(jax.random.fold_in(k, 1), (8,)) * 0.5 + 1.0
    beta = jax.random.normal(jax.random.fold_in(k, 2), (8,)) * 0.1
    ct = jax.random.normal(jax.random.fold_in(k, 3), (4, 5, 5, 8), dtype)

    y, mean, var = bn_act(x, gamma, beta, EPS, relu)
    y_ref = _ref_bn(x, gamma, beta, relu)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(y_ref, np.float32),
        rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5, atol=1e-5,
    )

    def loss_fused(x, g, b):
        y, _, _ = bn_act(x, g, b, EPS, relu)
        return jnp.sum(y.astype(jnp.float32) * ct.astype(jnp.float32))

    def loss_ref(x, g, b):
        return jnp.sum(
            _ref_bn(x, g, b, relu).astype(jnp.float32)
            * ct.astype(jnp.float32)
        )

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    rtol = 5e-2 if dtype == jnp.bfloat16 else 1e-4
    for a, b, nm in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=rtol, atol=rtol, err_msg=nm,
        )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_module_matches_flax_batchnorm(dtype):
    """Train + eval forward and EMA updates vs nn.BatchNorm (fp32 stats)."""
    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (8, 4, 4, 6), dtype)

    fused = FusedBatchNorm(use_running_average=False, momentum=0.9)
    flaxbn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                          dtype=jnp.float32)
    vf = fused.init(k, x)
    vx = flaxbn.init(k, x.astype(jnp.float32))
    # same initial structure
    assert jax.tree_util.tree_structure(vf) == jax.tree_util.tree_structure(vx)

    yf, mf = fused.apply(vf, x, mutable=["batch_stats"])
    yx, mx = flaxbn.apply(vx, x.astype(jnp.float32), mutable=["batch_stats"])
    rtol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(
        np.asarray(yf, np.float32), np.asarray(yx.astype(dtype), np.float32),
        rtol=rtol, atol=1e-5,
    )
    for kk in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(mf["batch_stats"][kk]),
            np.asarray(mx["batch_stats"][kk]),
            rtol=1e-4, atol=1e-5, err_msg=kk,
        )

    # eval mode with non-trivial running stats
    vf2 = {"params": vf["params"], "batch_stats": mf["batch_stats"]}
    ev_f = FusedBatchNorm(use_running_average=True).apply(vf2, x)
    ev_x = nn.BatchNorm(use_running_average=True, dtype=jnp.float32).apply(
        {"params": vx["params"], "batch_stats": mx["batch_stats"]},
        x.astype(jnp.float32),
    )
    np.testing.assert_allclose(
        np.asarray(ev_f, np.float32),
        np.asarray(ev_x.astype(dtype), np.float32),
        rtol=rtol, atol=1e-5,
    )


def test_unnamed_call_sites_produce_identical_trees(monkeypatch):
    """fp32_batch_norm with NO name must auto-name identically under both
    implementations (flax names from the class name — the fused class is
    deliberately called BatchNorm so unnamed DARTS-style call sites don't
    fork the param tree between the A/B paths)."""
    from fedml_tpu.models.norms import fp32_batch_norm

    class Body(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            return fp32_batch_norm(train)(x)

    trees = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("FEDML_TPU_FUSED_BN", flag)
        v = Body().init(jax.random.PRNGKey(0), jnp.zeros((2, 3, 3, 4)))
        trees[flag] = jax.tree_util.tree_structure(v)
    assert trees["1"] == trees["0"]


def test_relu_fold_matches_explicit_relu_module():
    k = jax.random.PRNGKey(2)
    x = jax.random.normal(k, (8, 4, 4, 6), jnp.float32)
    mod = FusedBatchNorm(use_running_average=False, relu=True)
    v = mod.init(k, x)
    y, _ = mod.apply(v, x, mutable=["batch_stats"])
    plain = FusedBatchNorm(use_running_average=False, relu=False)
    y2, _ = plain.apply(v, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(jnp.maximum(y2, 0)))


def test_resnet_step_equivalent_under_ab_switch(monkeypatch):
    """resnet56 local train: fused vs plain nn.BatchNorm paths agree.

    Tolerances are loose relative to the single-layer tests above (which
    pin exactness): 57 stacked BNs amplify benign rsqrt/fma rounding
    differences to ~1e-2 in post-update params. This test guards the
    WIRING — identical variable trees, both batch_stats collections
    updated, losses equal — not per-op numerics."""
    from fedml_tpu.config import TrainConfig
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.resnet import CifarResNet
    from fedml_tpu.train.client import make_local_train

    x = np.random.RandomState(0).randn(2, 4, 32, 32, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, (2, 4)).astype(np.int32)
    mask = np.ones((2, 4), np.float32)
    tc = TrainConfig(client_optimizer="sgd", lr=0.1)

    outs = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("FEDML_TPU_FUSED_BN", flag)
        # one block per stage: same wiring (stem + all three BN shapes +
        # downsample) at a fraction of resnet56's compile time
        model = ModelDef(
            module=CifarResNet(layers=(1, 1, 1), num_classes=10),
            input_shape=(32, 32, 3),
            num_classes=10,
            has_batch_stats=True,
        )
        variables = model.init(jax.random.PRNGKey(0))
        lt = make_local_train(model, tc, epochs=1)
        v2, mets = lt(
            variables, jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask),
            jax.random.PRNGKey(3),
        )
        outs[flag] = (v2, mets)

    assert jax.tree_util.tree_structure(
        outs["1"][0]
    ) == jax.tree_util.tree_structure(outs["0"][0])
    for a, b in zip(
        jax.tree_util.tree_leaves(outs["1"][0]),
        jax.tree_util.tree_leaves(outs["0"][0]),
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-1, atol=2e-2,
        )
    np.testing.assert_allclose(
        float(outs["1"][1]["loss_sum"]), float(outs["0"][1]["loss_sum"]),
        rtol=1e-3,
    )
    # batch_stats moved off their init values in both paths
    for flag in ("1", "0"):
        bs = outs[flag][0]["batch_stats"]
        first = jax.tree_util.tree_leaves(bs)[0]
        assert float(jnp.abs(np.asarray(first)).sum()) > 0
