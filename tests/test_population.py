"""fedml_tpu/population/ — the million-client population runtime
(ISSUE 11 / ROADMAP item 1).

Pins the subsystem's contracts:
- alias sampler statistical correctness (chi-square against the weight
  vector) and determinism (same seed ⇒ byte-identical cohorts across
  processes; legacy-identical below the threshold);
- PopulationIndex shape classes == the scalar partition_shape_classes,
  save/load/mmap roundtrip;
- ShardedClientState bit-parity with MmapClientState, and a SCAFFOLD
  run bit-identical across the mmap and sharded spill tiers;
- bounded scheduler checkpoint (the O(N)-loss-map regression);
- bounded health registry: LRU active set preserves exact counters
  through eviction, registry-wide trace byte budget marks clients
  trace_incomplete and replay refuses them;
- sim/transport cohort parity with the O(cohort) paths forced on.
"""

import dataclasses
import json
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from fedml_tpu.population import (
    AliasSampler,
    BoundedLossMap,
    PopulationIndex,
    draw_uniform_distinct,
)

jax = pytest.importorskip("jax")


# ---------------------------------------------------------------------------
# alias sampler — statistical correctness
# ---------------------------------------------------------------------------


def test_alias_sampler_chi_square_matches_weights():
    """With-replacement alias draws must follow the weight vector: a
    chi-square statistic over 200k draws stays within a 6-sigma normal
    approximation of its df — deterministic seed, no scipy."""
    rng = np.random.default_rng(7)
    w = rng.random(256) ** 2 + 1e-3
    t = AliasSampler(w)
    m = 200_000
    draws = t.sample(np.random.default_rng(1234), m)
    obs = np.bincount(draws, minlength=256).astype(np.float64)
    exp = t.p * m
    chi2 = float(np.sum((obs - exp) ** 2 / exp))
    df = 255
    assert chi2 < df + 6 * np.sqrt(2 * df), chi2
    # and not suspiciously UNIFORM either: against equal weights the
    # same statistic must blow up (the draws really are biased)
    exp_uniform = np.full(256, m / 256)
    chi2_uniform = float(np.sum((obs - exp_uniform) ** 2 / exp_uniform))
    assert chi2_uniform > 10 * df, chi2_uniform


def test_alias_distinct_draw_matches_legacy_distribution():
    """draw_distinct (rejection + dedupe) is distributionally identical
    to the legacy exact without-replacement draw: per-client inclusion
    frequencies over many rounds agree within sampling noise."""
    rng = np.random.default_rng(3)
    w = rng.random(40) + 0.05
    t = AliasSampler(w)
    n_rounds, k = 4000, 6
    inc_alias = np.zeros(40)
    inc_legacy = np.zeros(40)
    p = w / w.sum()
    for r in range(n_rounds):
        inc_alias[t.draw_distinct(np.random.default_rng([5, r]), k)] += 1
        inc_legacy[
            np.random.default_rng([6, r]).choice(40, k, replace=False, p=p)
        ] += 1
    diff = np.abs(inc_alias - inc_legacy) / n_rounds
    assert diff.max() < 0.04, diff.max()


def test_alias_distinct_draw_properties():
    t = AliasSampler(np.arange(1, 101, dtype=np.float64))
    d = t.draw_distinct(np.random.default_rng(0), 17)
    assert len(d) == 17 and len(set(d.tolist())) == 17
    # zero-weight tolerance: request beyond the weighted support fills
    # uniformly from the zero-weight ids (the Dirichlet-shard contract)
    w = np.zeros(50)
    w[:8] = 1.0
    d = AliasSampler(w).draw_distinct(np.random.default_rng(1), 20)
    assert len(set(d.tolist())) == 20
    assert set(range(8)) <= set(d.tolist())


def test_draw_uniform_distinct_excludes_and_bounds():
    ex = np.asarray([1, 2, 3], np.int64)
    d = draw_uniform_distinct(np.random.default_rng(0), 1_000_000, 12, exclude=ex)
    assert len(set(d.tolist())) == 12
    assert not (set(d.tolist()) & {1, 2, 3})
    # dense fallback when the request is a large population fraction:
    # the draw clamps to the eligible set and still excludes
    d = draw_uniform_distinct(np.random.default_rng(0), 10, 9, exclude=ex)
    assert len(d) == 7 and sorted(d.tolist()) == [0, 4, 5, 6, 7, 8, 9]


def test_alias_draws_byte_identical_across_processes():
    """Same (weights, seed) ⇒ byte-identical cohort in a fresh process —
    the scheduler's cross-process determinism contract."""
    code = (
        "import numpy as np\n"
        "from fedml_tpu.population import AliasSampler\n"
        "t = AliasSampler(np.arange(1, 1001, dtype=np.float64))\n"
        "d = t.draw_distinct(np.random.default_rng([9, 42]), 16)\n"
        "print(','.join(map(str, d.tolist())))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, check=True,
        env={**__import__('os').environ, "JAX_PLATFORMS": "cpu"},
    ).stdout.strip()
    t = AliasSampler(np.arange(1, 1001, dtype=np.float64))
    here = t.draw_distinct(np.random.default_rng([9, 42]), 16)
    assert out == ",".join(map(str, here.tolist()))


# ---------------------------------------------------------------------------
# threshold semantics — legacy below, O(cohort) above
# ---------------------------------------------------------------------------


def _ctx(n, counts=None, threshold=65536):
    from fedml_tpu.scheduler.policies import SelectionContext

    return SelectionContext(
        seed=5,
        num_clients=n,
        sample_counts=(
            np.asarray(counts, np.int64) if counts is not None else None
        ),
        ocohort_threshold=threshold,
    )


def test_weighted_policy_legacy_below_threshold():
    """Below the population threshold the weighted draw is the legacy
    exact numpy draw, byte-for-byte — historical cohorts never change."""
    from fedml_tpu.scheduler.policies import (
        WeightedPolicy, _rng, _size_probs, _weighted_draw,
    )

    counts = np.arange(1, 33)
    ctx = _ctx(32, counts)
    sel = WeightedPolicy().select(4, 6, ctx)
    rng = _rng(_ctx(32, counts), 4, salt=1)
    legacy = _weighted_draw(rng, 32, 6, _size_probs(_ctx(32, counts)))
    np.testing.assert_array_equal(sel, legacy)
    assert ctx.index is None  # the O(cohort) machinery never engaged


def test_weighted_policy_alias_at_threshold():
    from fedml_tpu.scheduler.policies import WeightedPolicy

    counts = np.arange(1, 33)
    ctx = _ctx(32, counts, threshold=16)
    sel = WeightedPolicy().select(4, 6, ctx)
    assert ctx.index is not None  # engaged and cached on the context
    assert len(set(sel.tolist())) == 6 and sel.max() < 32
    # round-keyed determinism through the same context
    np.testing.assert_array_equal(sel, WeightedPolicy().select(4, 6, ctx))


def test_power_of_choice_alias_candidates_respect_losses():
    from fedml_tpu.scheduler.policies import PowerOfChoicePolicy

    counts = np.full(64, 10)
    ctx = _ctx(64, counts, threshold=16)
    ctx.losses = {i: (10.0 if i % 2 else 0.1) for i in range(64)}
    sel = PowerOfChoicePolicy(candidate_factor=4.0).select(1, 8, ctx)
    # high-loss (odd) clients dominate the kept top-k
    assert sum(int(c) % 2 for c in sel) >= 6, sel


# ---------------------------------------------------------------------------
# PopulationIndex
# ---------------------------------------------------------------------------


def test_population_index_shape_classes_match_scalar():
    from fedml_tpu.data.base import bucket_steps, partition_shape_classes

    rng = np.random.default_rng(0)
    counts = rng.integers(0, 900, 3000)
    for bs, pb in ((16, 1), (8, 4), (32, 8)):
        legacy = {}
        for i, n in enumerate(counts):
            legacy.setdefault(bucket_steps([int(n)], bs, pb)[:2], i)
        assert partition_shape_classes(counts, bs, pb) == legacy
        assert PopulationIndex(counts).shape_classes(bs, pb) == legacy
    # full-batch mode keeps the scalar loop and still agrees
    legacy = {}
    for i, n in enumerate(counts[:64]):
        legacy.setdefault(bucket_steps([int(n)], -1, 1)[:2], i)
    assert PopulationIndex(counts[:64]).shape_classes(-1, 1) == legacy


def test_population_index_save_load_and_mmap_backing(tmp_path):
    counts = np.random.default_rng(1).integers(1, 100, 10_000)
    idx = PopulationIndex.from_counts(
        counts, path=str(tmp_path / "idx"), mmap_threshold_bytes=1024
    )
    # above the threshold the packed counts reopen mmap-backed, from a
    # content-digest-keyed subdirectory of the (shareable) parent dir
    assert isinstance(idx.counts, np.memmap)
    np.testing.assert_array_equal(np.asarray(idx.counts), counts)
    subs = [p for p in (tmp_path / "idx").iterdir() if p.is_dir()]
    assert len(subs) == 1 and subs[0].name.startswith("pop_10000_")
    re = PopulationIndex.load(str(subs[0]))
    np.testing.assert_array_equal(np.asarray(re.counts), counts)
    assert re.total_samples() == int(counts.sum())
    np.testing.assert_array_equal(
        re.cohort_counts([5, 17, 99]), counts[[5, 17, 99]]
    )
    # a second session with the SAME dataset reuses the one copy; a
    # DIFFERENT dataset gets its own subdir (no cross-session clobber)
    PopulationIndex.from_counts(
        counts, path=str(tmp_path / "idx"), mmap_threshold_bytes=1024
    )
    other = np.random.default_rng(2).integers(1, 100, 10_000)
    o = PopulationIndex.from_counts(
        other, path=str(tmp_path / "idx"), mmap_threshold_bytes=1024
    )
    np.testing.assert_array_equal(np.asarray(o.counts), other)
    np.testing.assert_array_equal(np.asarray(idx.counts), counts)  # intact
    assert len([p for p in (tmp_path / "idx").iterdir() if p.is_dir()]) == 2
    # below the threshold: plain in-RAM array, nothing persisted
    small = PopulationIndex.from_counts(counts[:4], path=None)
    assert not isinstance(small.counts, np.memmap)


def test_live_selection_memo_is_bounded():
    from fedml_tpu.scheduler import ClientScheduler

    sched = ClientScheduler(
        num_clients=100, k=4, policy="weighted", seed=0,
        sample_counts=np.full(100, 10), selection_memo_rounds=16,
    )
    for r in range(300):
        sched.select(r)
    assert len(sched._selections) == 64  # max(memo_rounds, 64) floor
    assert min(sched._selections) == 236  # most recent rounds kept
    # evicted rounds re-derive identically (pure in (seed, round))
    fresh = ClientScheduler(
        num_clients=100, k=4, policy="weighted", seed=0,
        sample_counts=np.full(100, 10),
    )
    np.testing.assert_array_equal(sched.select(5), fresh.select(5))


def test_dataset_population_index_accessors():
    from fedml_tpu.data.base import FederatedDataset

    data = FederatedDataset(
        name="t",
        client_x=[np.zeros((i + 1, 2), np.float32) for i in range(5)],
        client_y=[np.zeros((i + 1,), np.int32) for i in range(5)],
        test_x=np.zeros((2, 2), np.float32),
        test_y=np.zeros((2,), np.int32),
        num_classes=2,
    )
    idx = data.population_index()
    np.testing.assert_array_equal(idx.counts, [1, 2, 3, 4, 5])


# ---------------------------------------------------------------------------
# sharded state tier
# ---------------------------------------------------------------------------


def _tree():
    return {
        "a": np.zeros((3, 4), np.float32),
        "b": {"c": np.arange(5, dtype=np.int32)},
    }


def test_sharded_state_bit_parity_with_mmap_store():
    from fedml_tpu.algorithms.state_store import MmapClientState
    from fedml_tpu.population.state_tier import ShardedClientState

    n = 500
    s1 = ShardedClientState(_tree(), n, shard_bits=6)
    s2 = MmapClientState(_tree(), n)
    rng = np.random.default_rng(0)
    for _ in range(15):
        ids = rng.choice(n, 9, replace=False)
        rows = {
            "a": rng.normal(size=(9, 3, 4)).astype(np.float32),
            "b": {"c": rng.integers(0, 9, (9, 5)).astype(np.int32)},
        }
        s1.scatter(ids, rows)
        s2.scatter(ids, rows)
        probe = rng.choice(n, 16, replace=False)
        g1, g2 = s1.gather(probe), s2.gather(probe)
        np.testing.assert_array_equal(g1["a"], g2["a"])
        np.testing.assert_array_equal(g1["b"]["c"], g2["b"]["c"])
    np.testing.assert_array_equal(s1.initialized_ids(), s2.initialized_ids())
    assert s1.initialized_count() == s2.initialized_count()
    # reset_to: both roll back to {init except kept rows}
    keep = s1.initialized_ids()[:3]
    kept_rows = s1.gather(keep)
    s1.reset_to(keep, kept_rows)
    s2.reset_to(keep, kept_rows)
    g1, g2 = s1.gather(np.arange(n)), s2.gather(np.arange(n))
    np.testing.assert_array_equal(g1["a"], g2["a"])


def test_sharded_state_lazy_init_and_reopen(tmp_path):
    from fedml_tpu.population.state_tier import ShardedClientState

    path = str(tmp_path / "store")
    s = ShardedClientState(_tree(), 100, path=path, shard_bits=5)
    g = s.gather([42])
    np.testing.assert_array_equal(g["b"]["c"][0], np.arange(5))  # init row
    assert s.initialized_count() == 0
    s.scatter([42], {
        "a": np.ones((1, 3, 4), np.float32),
        "b": {"c": np.full((1, 5), 7, np.int32)},
    })
    s.flush()
    # reopen: same layout resumes; rows survive
    s2 = ShardedClientState(_tree(), 100, path=path, shard_bits=5)
    np.testing.assert_array_equal(s2.gather([42])["b"]["c"][0], np.full(5, 7))
    assert s2.initialized_count() == 1
    # layout mismatch refuses loudly
    with pytest.raises(ValueError):
        ShardedClientState(_tree(), 101, path=path, shard_bits=5)
    with pytest.raises(ValueError):
        ShardedClientState(_tree(), 100, path=path, shard_bits=6)


def _scaffold_cfg(n, store, state_dir):
    from fedml_tpu.config import (
        DataConfig, FedConfig, RunConfig, TrainConfig,
    )

    return RunConfig(
        data=DataConfig(batch_size=8, device_cache=False),
        fed=FedConfig(
            client_num_in_total=n, client_num_per_round=4, comm_round=3,
            epochs=1, frequency_of_the_test=100,
            state_store=store, state_dir=state_dir,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def test_scaffold_sharded_tier_bit_identical_to_mmap():
    """The money contract: a SCAFFOLD run on the sharded record-major
    tier is BIT-IDENTICAL to the mmap-per-leaf run at the same seed
    (test_state_spill pins mmap == device, so all three agree)."""
    from fedml_tpu.algorithms.scaffold import ScaffoldAPI
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model

    data = synthetic_classification(
        num_clients=12, num_classes=3, feat_shape=(6,),
        samples_per_client=24, partition_method="homo", seed=0,
    )
    outs = {}
    for store in ("mmap", "sharded"):
        model = create_model("lr", "synthetic", (6,), 3)
        api = ScaffoldAPI(
            _scaffold_cfg(12, store, tempfile.mkdtemp()), data, model
        )
        assert api._state_mode == store
        for r in range(3):
            api.train_round(r)
        outs[store] = (
            jax.device_get(api.global_vars),
            jax.device_get(api.c_server),
            api._c_store.gather(api._c_store.initialized_ids()),
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(outs["mmap"]),
        jax.tree_util.tree_leaves(outs["sharded"]),
    ):
        np.testing.assert_array_equal(a, b)


def test_resolve_state_store_sharded_auto():
    from fedml_tpu.algorithms.state_store import resolve_state_store
    from fedml_tpu.config import FedConfig, PopulationConfig

    fed = FedConfig(state_store="auto", state_budget_bytes=1000)
    pop = PopulationConfig(ocohort_threshold=1000)
    assert resolve_state_store(fed, 999, n_clients=5000, population=pop) == "device"
    assert resolve_state_store(fed, 1001, n_clients=5000, population=pop) == "sharded"
    assert resolve_state_store(fed, 1001, n_clients=10, population=pop) == "mmap"
    assert resolve_state_store(FedConfig(state_store="sharded"), 1) == "sharded"
    with pytest.raises(ValueError):
        resolve_state_store(FedConfig(state_store="hbm"), 1)


# ---------------------------------------------------------------------------
# bounded scheduler checkpoint (the O(N) loss-map regression)
# ---------------------------------------------------------------------------


def test_bounded_loss_map_eviction_order():
    m = BoundedLossMap(3)
    for i in range(5):
        m[i] = float(i)
    assert sorted(m.keys()) == [2, 3, 4]
    m[2] = 9.0  # refresh
    m[5] = 5.0  # evicts 3 (stalest), not 2
    assert sorted(m.keys()) == [2, 4, 5]
    assert m.get(3) is None and m.get(2) == 9.0


def test_scheduler_checkpoint_stays_bounded():
    """Feed far more client losses and rounds than the bounds: the
    persisted `sched` slot must stay at the configured capacity — the
    O(N)-checkpoint-growth regression test (ISSUE 11 satellite)."""
    from fedml_tpu.scheduler import ClientScheduler

    sched = ClientScheduler(
        num_clients=200_000, k=4, policy="power_of_choice", seed=0,
        sample_counts=np.full(200_000, 10),
        loss_map_capacity=512, selection_memo_rounds=16,
    )
    for cid in range(0, 200_000, 2):  # 100k reported losses
        sched.report_loss(cid, float(cid % 17))
    for r in range(64):
        sched.select(r)
    state = sched.state_dict()
    assert len(state["loss_ids"]) == 512
    assert len(state["rounds"]) == 16
    assert int(state["rounds"][0]) == 48  # the most RECENT rounds persist
    total_bytes = sum(
        np.asarray(v).nbytes
        for v in [state["rounds"], state["loss_ids"], state["loss_vals"]]
    ) + sum(np.asarray(s).nbytes for s in state["selections"])
    assert total_bytes < 64 * 1024, total_bytes
    # roundtrip preserves the bound and the entries
    fresh = ClientScheduler(
        num_clients=200_000, k=4, policy="power_of_choice", seed=0,
        sample_counts=np.full(200_000, 10), loss_map_capacity=512,
    )
    fresh.load_state_dict(state)
    assert len(fresh._ctx.losses) == 512
    np.testing.assert_array_equal(fresh.select(60), sched.select(60))


# ---------------------------------------------------------------------------
# bounded health registry
# ---------------------------------------------------------------------------


def _registry(**kw):
    from fedml_tpu.telemetry.health import ClientHealthRegistry
    from fedml_tpu.telemetry.metrics import MetricsRegistry

    return ClientHealthRegistry(registry=MetricsRegistry(), **kw)


def test_health_active_set_eviction_preserves_exact_counters():
    reg = _registry(max_active_clients=4)
    for r in range(3):
        for cid in range(10):
            reg.observe_train(cid, r, 0.1)
    # all 10 participated 3 rounds — exact through eviction + revival
    assert reg.clients_seen() == list(range(10))
    for cid in range(10):
        assert reg.rounds_participated(cid) == 3, cid
        assert reg.last_seen_round(cid) == 2
    # only the active set carries timing windows
    with_means = [c for c in range(10) if reg.mean_train_s(c) is not None]
    assert len(with_means) == 4
    snap = reg.snapshot()
    assert len(snap) == 10
    assert all(rec["rounds_participated"] == 3 for rec in snap.values())


def test_health_fault_tallies_exact_through_eviction():
    reg = _registry(max_active_clients=2)
    for cid in range(6):
        reg.observe_fault(cid, 0, "dropout")
        reg.observe_fault(cid, 1, "dropout")
    for cid in range(6):
        assert reg.faults(cid) == {"dropout": 2}, cid
    trace = reg.export_trace()
    assert all(
        rec["faults"]["dropout"] == [[0, 0.0], [1, 0.0]]
        for rec in trace.clients.values()
    )


def test_health_trace_budget_marks_incomplete_and_replay_refuses():
    from fedml_tpu.scheduler.faults import FaultPlan

    reg = _registry(trace_budget_bytes=96 * 5)  # room for 5 events
    for i in range(8):
        reg.observe_fault(100 + i, i, "dropout")
    assert reg.trace_incomplete
    trace = reg.export_trace()
    complete = [c for c, r in trace.clients.items() if r["trace_complete"]]
    dropped = [c for c, r in trace.clients.items() if not r["trace_complete"]]
    assert len(complete) == 5 and len(dropped) == 3
    # tallies stay exact even for dropped clients
    assert all(reg.faults(c) == {"dropout": 1} for c in dropped)
    # refusal semantics: a truncated fleet must not replay silently
    with pytest.raises(ValueError, match="cannot replay"):
        FaultPlan.from_trace(trace)
    # an unexhausted registry replays fine
    ok = _registry()
    ok.observe_fault(1, 0, "dropout")
    assert not ok.trace_incomplete
    FaultPlan.from_trace(ok.export_trace())


def test_health_from_config_applies_population_bounds():
    from fedml_tpu.config import PopulationConfig, RunConfig
    from fedml_tpu.telemetry.health import ClientHealthRegistry
    from fedml_tpu.telemetry.metrics import MetricsRegistry

    cfg = RunConfig(
        population=PopulationConfig(
            health_active_clients=7, health_trace_budget_bytes=123,
        )
    )
    reg = ClientHealthRegistry.from_config(cfg, registry=MetricsRegistry())
    assert reg._clients.capacity == 7
    assert reg.trace_budget_bytes == 123


# ---------------------------------------------------------------------------
# parity with the O(cohort) paths forced on
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("policy", ["weighted", "power_of_choice"])
def test_sim_transport_parity_with_ocohort_engaged(policy):
    """The existing parity contract, re-pinned with the population
    threshold forced below N so every draw goes through the alias
    machinery: simulator and loopback transport still select
    byte-identical cohorts from one config."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
    from fedml_tpu.config import (
        DataConfig, FedConfig, PopulationConfig, RunConfig, TrainConfig,
    )
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model

    data = synthetic_classification(
        num_clients=16, num_classes=3, feat_shape=(6,),
        samples_per_client=24, partition_method="hetero", seed=0,
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=8, device_cache=False),
        fed=FedConfig(
            client_num_in_total=16, client_num_per_round=4, comm_round=3,
            selection=policy, frequency_of_the_test=10,
        ),
        train=TrainConfig(lr=0.1),
        population=PopulationConfig(ocohort_threshold=8),
        seed=2,
    )
    model = create_model("lr", "synthetic", (6,), 3)
    api = FedAvgAPI(cfg, data, model)
    assert api.scheduler._ctx.index is not None
    api.train()
    server = run_loopback_federation(cfg, data, model)
    assert api.scheduler.selections() == server.scheduler.selections()
