"""Pretrained-weights path (ref resnet56(pretrained=True, path=...),
fedml_api/model/cv/resnet.py:200-222): torch .pth import into the Flax
resnet56, export back, and the npz save/load recipe."""

import numpy as np
import pytest

from fedml_tpu.models import create_model
from fedml_tpu.models.pretrained import (
    export_torch_state_dict,
    import_torch_state_dict,
    load_pretrained,
    load_torch_checkpoint,
    save_pretrained,
)


@pytest.fixture(scope="module")
def template():
    import jax

    model = create_model("resnet56", "cifar10", (16, 16, 3), 10)
    return model, model.init(jax.random.PRNGKey(0))


def _leaves(tree):
    import jax

    return [np.asarray(a) for a in jax.tree_util.tree_leaves(tree)]


def test_torch_roundtrip(template):
    _, variables = template
    sd = export_torch_state_dict(variables)
    # reference naming spot checks
    assert "conv1.weight" in sd
    assert "layer1.0.conv1.weight" in sd
    assert "layer2.0.downsample.0.weight" in sd
    assert "layer2.0.downsample.1.running_mean" in sd
    assert "fc.weight" in sd and "fc.bias" in sd
    assert sd["conv1.weight"].shape[0] == 16  # torch OIHW: O first
    back = import_torch_state_dict(sd, variables)
    for a, b in zip(_leaves(variables), _leaves(back)):
        np.testing.assert_array_equal(a, b)


def test_torch_pth_file_with_module_prefix(template, tmp_path):
    torch = pytest.importorskip("torch")
    _, variables = template
    sd = {
        "module." + k: torch.from_numpy(np.ascontiguousarray(v))
        for k, v in export_torch_state_dict(variables).items()
    }
    path = tmp_path / "resnet56.pth"
    torch.save({"state_dict": sd}, path)  # reference checkpoint format
    back = load_torch_checkpoint(str(path), variables)
    for a, b in zip(_leaves(variables), _leaves(back)):
        np.testing.assert_array_equal(a, b)


def test_imported_weights_run_forward(template):
    import jax

    model, variables = template
    back = import_torch_state_dict(export_torch_state_dict(variables), variables)
    x = np.random.default_rng(0).normal(size=(2, 16, 16, 3)).astype(np.float32)
    ref_out, _ = model.apply(variables, x, train=False)
    out, _ = model.apply(back, x, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), atol=1e-6)


def test_npz_recipe_and_shape_guard(template, tmp_path):
    _, variables = template
    path = str(tmp_path / "weights.npz")
    save_pretrained(path, variables)
    back = load_pretrained(path, variables)
    for a, b in zip(_leaves(variables), _leaves(back)):
        np.testing.assert_array_equal(a, b)

    sd = export_torch_state_dict(variables)
    sd["fc.weight"] = sd["fc.weight"][:, :3]
    with pytest.raises(ValueError):
        import_torch_state_dict(sd, variables)
    del sd["fc.weight"]
    with pytest.raises(KeyError):
        import_torch_state_dict(sd, variables)


def test_create_model_pretrained_kwarg(template, tmp_path):
    import jax

    _, variables = template
    path = str(tmp_path / "w.npz")
    save_pretrained(path, variables)
    loaded = create_model(
        "resnet56", "cifar10", (16, 16, 3), 10, pretrained=path
    )
    got = loaded.init(jax.random.PRNGKey(123))  # rng must not matter
    for a, b in zip(_leaves(variables), _leaves(got)):
        np.testing.assert_array_equal(a, b)
