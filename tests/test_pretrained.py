"""Pretrained-weights path (ref resnet56(pretrained=True, path=...),
fedml_api/model/cv/resnet.py:200-222): torch .pth import into the Flax
resnet56, export back, and the npz save/load recipe."""

import numpy as np
import pytest

from fedml_tpu.models import create_model
from fedml_tpu.models.pretrained import (
    export_torch_state_dict,
    import_torch_state_dict,
    load_pretrained,
    load_torch_checkpoint,
    save_pretrained,
)


@pytest.fixture(scope="module")
def template():
    import jax

    model = create_model("resnet56", "cifar10", (16, 16, 3), 10)
    return model, model.init(jax.random.PRNGKey(0))


def _leaves(tree):
    import jax

    return [np.asarray(a) for a in jax.tree_util.tree_leaves(tree)]


def test_torch_roundtrip(template):
    _, variables = template
    sd = export_torch_state_dict(variables)
    # reference naming spot checks
    assert "conv1.weight" in sd
    assert "layer1.0.conv1.weight" in sd
    assert "layer2.0.downsample.0.weight" in sd
    assert "layer2.0.downsample.1.running_mean" in sd
    assert "fc.weight" in sd and "fc.bias" in sd
    assert sd["conv1.weight"].shape[0] == 16  # torch OIHW: O first
    back = import_torch_state_dict(sd, variables)
    for a, b in zip(_leaves(variables), _leaves(back)):
        np.testing.assert_array_equal(a, b)


def test_torch_pth_file_with_module_prefix(template, tmp_path):
    torch = pytest.importorskip("torch")
    _, variables = template
    sd = {
        "module." + k: torch.from_numpy(np.ascontiguousarray(v))
        for k, v in export_torch_state_dict(variables).items()
    }
    path = tmp_path / "resnet56.pth"
    torch.save({"state_dict": sd}, path)  # reference checkpoint format
    back = load_torch_checkpoint(str(path), variables)
    for a, b in zip(_leaves(variables), _leaves(back)):
        np.testing.assert_array_equal(a, b)


def test_imported_weights_run_forward(template):
    import jax

    model, variables = template
    back = import_torch_state_dict(export_torch_state_dict(variables), variables)
    x = np.random.default_rng(0).normal(size=(2, 16, 16, 3)).astype(np.float32)
    ref_out, _ = model.apply(variables, x, train=False)
    out, _ = model.apply(back, x, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), atol=1e-6)


def test_npz_recipe_and_shape_guard(template, tmp_path):
    _, variables = template
    path = str(tmp_path / "weights.npz")
    save_pretrained(path, variables)
    back = load_pretrained(path, variables)
    for a, b in zip(_leaves(variables), _leaves(back)):
        np.testing.assert_array_equal(a, b)

    sd = export_torch_state_dict(variables)
    sd["fc.weight"] = sd["fc.weight"][:, :3]
    with pytest.raises(ValueError):
        import_torch_state_dict(sd, variables)
    del sd["fc.weight"]
    with pytest.raises(KeyError):
        import_torch_state_dict(sd, variables)


def test_create_model_pretrained_kwarg(template, tmp_path):
    import jax

    _, variables = template
    path = str(tmp_path / "w.npz")
    save_pretrained(path, variables)
    loaded = create_model(
        "resnet56", "cifar10", (16, 16, 3), 10, pretrained=path
    )
    got = loaded.init(jax.random.PRNGKey(123))  # rng must not matter
    for a, b in zip(_leaves(variables), _leaves(got)):
        np.testing.assert_array_equal(a, b)


def test_committed_pretrained_resnet56_artifact_loads_and_performs():
    """The repo ships a REAL trained checkpoint (VERDICT r4 Missing #1):
    fedml_tpu/models/pretrained_weights/resnet56_cifar10_synth.npz,
    trained by examples/train_pretrained_resnet56.py on the synthetic
    cross-silo CIFAR-10 regime (the ref ships torch .pth checkpoints for
    resnet56 — resnet.py:200-222; real downloads are unavailable here, so
    the artifact's regime is the synthetic stand-in, recorded in the
    sibling .json). create_model(pretrained=...) must load it and
    reproduce the recorded accuracy on the regenerated dataset."""
    import json
    import os

    import jax
    import numpy as np

    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model
    from fedml_tpu.train.evaluate import evaluate

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(
        root, "fedml_tpu", "models", "pretrained_weights",
        "resnet56_cifar10_synth.npz",
    )
    with open(path.replace(".npz", ".json")) as f:
        meta = json.load(f)
    model = create_model(
        "resnet56", "cifar10", (32, 32, 3), 10, pretrained=path
    )
    variables = model.init(jax.random.PRNGKey(123))  # = the loaded weights
    # regenerate the EXACT dataset the meta records (deterministic seed)
    data = synthetic_classification(
        num_clients=10, num_classes=10, feat_shape=(32, 32, 3),
        samples_per_client=512, partition_method="homo", ragged=False,
        seed=0,
    )
    _, acc = evaluate(model, variables, data.test_x, data.test_y)
    # recorded 1.0 on-chip; CPU forward numerics may flip a borderline
    # sample or two
    assert float(acc) >= meta["test_acc"] - 0.03, (acc, meta)
    # and an untrained init is nowhere near it (the artifact carries real
    # training, not a lucky init)
    plain = create_model("resnet56", "cifar10", (32, 32, 3), 10)
    _, acc0 = evaluate(
        plain, plain.init(jax.random.PRNGKey(0)), data.test_x, data.test_y
    )
    assert float(acc0) < 0.5
