"""Asynchronous buffered aggregation (algorithms/fedbuff.py) — the
barrier-free leg the reference lacks entirely (its aggregator barrier
waits for every worker forever, ref FedAVGAggregator.py:43-49)."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedbuff import (
    apply_buffered_update,
    run_fedbuff_loopback,
    staleness_weight,
)
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model


def test_staleness_discount_shape():
    w = staleness_weight(jnp.arange(5), exp=0.5)
    assert float(w[0]) == 1.0  # fresh delta is undiscounted
    assert np.all(np.diff(np.asarray(w)) < 0)  # staler => smaller
    # exp=0 disables the discount entirely
    assert np.allclose(np.asarray(staleness_weight(jnp.arange(5), 0.0)), 1.0)


def _random_tree(key, scale=1.0):
    k1, k2 = jax.random.split(key)
    return {
        "params": {
            "w": scale * jax.random.normal(k1, (3, 4)),
            "b": scale * jax.random.normal(k2, (4,)),
        }
    }


def test_fresh_buffer_step_equals_fedavg_average():
    """Degenerate-config oracle (the federated==centralized discipline of
    CI-script-fedavg.sh:42-48, applied to async): with every delta at
    staleness 0, eta_g=1 and equal shard sizes, one buffered step equals
    the synchronous FedAvg average of the k local models."""
    from fedml_tpu.algorithms.fedavg import weighted_average

    key = jax.random.PRNGKey(0)
    global_vars = _random_tree(key)
    locals_ = [_random_tree(jax.random.fold_in(key, i + 1)) for i in range(4)]
    deltas = [
        jax.tree_util.tree_map(lambda a, b: a - b, w, global_vars)
        for w in locals_
    ]
    buffered = apply_buffered_update(
        global_vars, deltas, taus=[0, 0, 0, 0], eta_g=1.0, exp=0.5
    )
    stacked = jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls), *locals_
    )
    fedavg = weighted_average(stacked, jnp.ones(4))
    for a, b in zip(
        jax.tree_util.tree_leaves(buffered), jax.tree_util.tree_leaves(fedavg)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_stale_deltas_are_downweighted():
    """A stale delta moves the model strictly less than a fresh one."""
    global_vars = {"params": {"w": jnp.zeros((2,))}}
    big = {"params": {"w": jnp.ones((2,))}}
    small = {"params": {"w": -jnp.ones((2,))}}
    fresh = apply_buffered_update(global_vars, [big, small], [0, 0], 1.0, 1.0)
    skew = apply_buffered_update(global_vars, [big, small], [0, 9], 1.0, 1.0)
    # equal staleness: the two opposite deltas cancel exactly
    np.testing.assert_allclose(np.asarray(fresh["params"]["w"]), 0.0, atol=1e-6)
    # the stale -1 delta is discounted, so the +1 delta dominates
    assert float(skew["params"]["w"][0]) > 0.5


def _cfg(comm_round, k, workers, total):
    return RunConfig(
        data=DataConfig(batch_size=16),
        fed=FedConfig(
            client_num_in_total=total,
            client_num_per_round=workers,
            comm_round=comm_round,
            epochs=1,
            frequency_of_the_test=5,
            async_buffer_k=k,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def test_async_loopback_federation_learns():
    """Live async federation over the loopback transport: 4 workers, buffer
    k=2 — the server must complete exactly comm_round buffer flushes,
    record a staleness histogram, and the model must learn."""
    data = synthetic_classification(
        num_clients=12, num_classes=4, feat_shape=(16,),
        samples_per_client=48, partition_method="homo", seed=0,
    )
    model = create_model("lr", "synthetic", (16,), 4)
    server = run_fedbuff_loopback(
        _cfg(comm_round=25, k=2, workers=4, total=12), data, model
    )
    assert server.server_steps == 25
    assert server.version == 25
    # every flush buffered k deltas
    assert len(server.staleness_seen) >= 25 * 2
    accs = [r["Test/Acc"] for r in server.history if "Test/Acc" in r]
    assert accs, "eval rows missing"
    assert accs[-1] > 0.8, f"async run failed to learn: {accs}"


def test_cli_fedbuff_loopback():
    """fedbuff is reachable from the unified CLI over the loopback
    transport; the final row is a server-step record."""
    import json

    from click.testing import CliRunner

    from fedml_tpu.cli import main

    result = CliRunner().invoke(
        main,
        [
            "--algorithm", "fedbuff", "--runtime", "loopback",
            "--dataset", "synthetic", "--model", "lr",
            "--client_num_in_total", "6", "--client_num_per_round", "3",
            "--comm_round", "4", "--batch_size", "8",
            "--async_buffer_k", "2", "--lr", "0.1",
        ],
    )
    assert result.exit_code == 0, result.output
    row = json.loads(result.output.strip().splitlines()[-1])
    assert row["server_step"] == 4
    assert "staleness_mean" in row


def test_cli_fedbuff_rejects_sync_runtime():
    from click.testing import CliRunner

    from fedml_tpu.cli import main

    result = CliRunner().invoke(
        main,
        ["--algorithm", "fedbuff", "--runtime", "vmap",
         "--dataset", "synthetic", "--model", "lr"],
    )
    assert result.exit_code != 0
    assert "loopback" in result.output


def test_async_federation_over_shm_and_mqtt():
    """The async protocol is transport-agnostic: the same run completes
    over the shared-memory transport and the embedded MQTT broker."""
    from fedml_tpu.algorithms.fedbuff import run_fedbuff_mqtt, run_fedbuff_shm

    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(8,), samples_per_client=24,
        partition_method="homo", seed=1,
    )
    model = create_model("lr", "synthetic", (8,), 3)
    for runner in (run_fedbuff_shm, run_fedbuff_mqtt):
        server = runner(_cfg(comm_round=6, k=2, workers=3, total=8), data, model)
        assert server.server_steps == 6, runner.__name__
        assert len(server.staleness_seen) >= 12, runner.__name__


def test_async_federation_over_real_grpc_sockets():
    """Async federation over REAL localhost gRPC sockets (the cross-silo
    transport, core/grpc_comm.py)."""
    from fedml_tpu.algorithms.fedbuff import run_fedbuff_federation
    from fedml_tpu.core.grpc_comm import GrpcCommManager

    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(8,), samples_per_client=24,
        partition_method="homo", seed=1,
    )
    model = create_model("lr", "synthetic", (8,), 3)
    ip = {r: "127.0.0.1" for r in range(4)}
    server = run_fedbuff_federation(
        _cfg(comm_round=5, k=2, workers=3, total=8), data, model,
        lambda rank: GrpcCommManager(rank, ip, base_port=18930),
    )
    assert server.server_steps == 5
    accs = [r for r in server.history if "Test/Acc" in r]
    assert accs


def test_async_survives_dead_worker():
    """Barrier-freedom under failure: a worker that dies mid-run (stops
    consuming and uploading) must not stall the server — the remaining
    workers' upload->redispatch pipeline keeps filling the buffer and the
    run completes every server step. The sync path would block on its
    barrier (that is the reference's forever-wait, FedAVGAggregator.py:
    43-49); the deadline/quorum FSM softens it; async needs NOTHING."""
    import threading
    import time

    from fedml_tpu.algorithms.fedbuff import (
        FedBuffClientManager,
        FedBuffServerManager,
    )
    from fedml_tpu.algorithms.fedavg_transport import LocalTrainer
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub

    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(8,), samples_per_client=24,
        partition_method="homo", seed=1,
    )
    model = create_model("lr", "synthetic", (8,), 3)
    cfg = _cfg(comm_round=8, k=2, workers=4, total=8)
    hub = LoopbackHub()
    server = FedBuffServerManager(
        cfg, LoopbackCommManager(hub, 0), model, data=data, worker_num=4
    )
    clients = [
        FedBuffClientManager(
            cfg, LoopbackCommManager(hub, rank), rank,
            LocalTrainer(cfg, data, model, "classification"),
        )
        for rank in range(1, 5)
    ]
    threads = [
        threading.Thread(target=c.run, daemon=True) for c in clients
    ]
    for t in threads:
        t.start()
    server.send_init_msg()
    # kill worker 1 almost immediately: it stops consuming dispatches
    killer = threading.Timer(0.2, clients[0].finish)
    killer.start()
    done = threading.Thread(target=server.run, daemon=True)
    done.start()
    done.join(timeout=120)
    assert not done.is_alive(), "async server stalled after a worker died"
    assert server.server_steps == 8
    for c in clients:
        c.finish()
    killer.cancel()


def test_async_worker_orphan_detection():
    """A worker whose server is genuinely dead (uploads undeliverable, no
    FINISH ever arrives) must exit VISIBLY as orphaned within its deadline
    — never hang forever parked on its inbox."""
    import threading

    from fedml_tpu.algorithms.fedavg_transport import LocalTrainer
    from fedml_tpu.algorithms.fedbuff import FedBuffClientManager
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
    from fedml_tpu.core.message import Message, MessageType as MT

    data = synthetic_classification(
        num_clients=2, num_classes=2, feat_shape=(4,), samples_per_client=8,
    )
    model = create_model("lr", "synthetic", (4,), 2)
    cfg = _cfg(comm_round=2, k=1, workers=1, total=2)
    hub = LoopbackHub()

    class DeadServerComm(LoopbackCommManager):
        def send_message(self, msg):
            if msg.get_receiver_id() == 0:
                raise ConnectionError("server gone")
            super().send_message(msg)

    client = FedBuffClientManager(
        cfg, DeadServerComm(hub, 1), 1,
        LocalTrainer(cfg, data, model, "classification"),
    )
    client.ORPHAN_DEADLINE_S = 0.5
    dispatch = Message(MT.S2C_INIT_CONFIG, 0, 1)
    dispatch.add_params(
        MT.ARG_MODEL_PARAMS,
        __import__("jax").device_get(
            model.init(__import__("jax").random.PRNGKey(0))
        ),
    )
    dispatch.add_params(MT.ARG_CLIENT_INDEX, 0)
    dispatch.add_params(MT.ARG_BASE_VERSION, 0)
    dispatch.add_params(MT.ARG_ROUND_IDX, 1)
    hub.deliver(dispatch)
    t = threading.Thread(target=client.run, daemon=True)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive(), "orphaned worker failed to exit"
    assert client.orphaned


def test_async_server_drops_duplicate_upload():
    """At-least-once delivery: a retried upload whose first copy WAS
    delivered (client-side RPC error after server-side receipt) must not
    be buffered twice — the dispatch tag dedupes it."""
    from fedml_tpu.algorithms.fedbuff import FedBuffServerManager
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
    from fedml_tpu.core.message import Message, MessageType as MT

    data = synthetic_classification(
        num_clients=4, num_classes=2, feat_shape=(4,), samples_per_client=8,
    )
    model = create_model("lr", "synthetic", (4,), 2)
    cfg = _cfg(comm_round=5, k=3, workers=2, total=4)
    server = FedBuffServerManager(
        cfg, LoopbackCommManager(LoopbackHub(), 0), model, data=data,
        worker_num=2,
    )
    delta = jax.device_get(
        jax.tree_util.tree_map(jnp.zeros_like, server.global_vars)
    )
    up = Message(MT.C2S_SEND_MODEL, 1, 0)
    up.add_params(MT.ARG_ASYNC_DELTA, delta)
    up.add_params(MT.ARG_NUM_SAMPLES, 8)
    up.add_params(MT.ARG_BASE_VERSION, 0)
    up.add_params(MT.ARG_ROUND_IDX, 7)  # dispatch tag
    server._on_delta_from_client(up)
    server._on_delta_from_client(up)  # the retry duplicate
    assert len(server._buffer) == 1
    # a NEW assignment (different tag) from the same worker is accepted
    up2 = Message(MT.C2S_SEND_MODEL, 1, 0)
    up2.add_params(MT.ARG_ASYNC_DELTA, delta)
    up2.add_params(MT.ARG_NUM_SAMPLES, 8)
    up2.add_params(MT.ARG_BASE_VERSION, 0)
    up2.add_params(MT.ARG_ROUND_IDX, 9)
    server._on_delta_from_client(up2)
    assert len(server._buffer) == 2


def test_async_duplicate_reply_resends_same_assignment():
    """A duplicate upload must be answered by RE-SENDING the worker's one
    outstanding assignment (same tag), never by minting a new one — else
    a client whose original reply WAS delivered ends up with two
    outstanding assignments and in-flight work grows unboundedly."""
    from fedml_tpu.algorithms.fedbuff import FedBuffServerManager
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
    from fedml_tpu.core.message import Message, MessageType as MT

    model = create_model("lr", "synthetic", (4,), 2)
    cfg = _cfg(comm_round=5, k=3, workers=2, total=4)
    server = FedBuffServerManager(
        cfg, LoopbackCommManager(LoopbackHub(), 0), model, worker_num=2,
    )
    sent = []
    server.send_message = lambda m: sent.append(m)

    def upload(tag):
        up = Message(MT.C2S_SEND_MODEL, 1, 0)
        up.add_params(
            MT.ARG_ASYNC_DELTA,
            jax.device_get(
                jax.tree_util.tree_map(jnp.zeros_like, server.global_vars)
            ),
        )
        up.add_params(MT.ARG_NUM_SAMPLES, 8)
        up.add_params(MT.ARG_BASE_VERSION, 0)
        up.add_params(MT.ARG_ROUND_IDX, tag)
        server._on_delta_from_client(up)

    upload(7)  # accepted: server replies with a fresh assignment
    assert len(sent) == 1
    fresh_tag = sent[0].get(MT.ARG_ROUND_IDX)
    fresh_client = sent[0].get(MT.ARG_CLIENT_INDEX)
    for _ in range(3):  # storm of duplicate retries
        upload(7)
    assert len(sent) == 4
    for m in sent[1:]:
        assert m.get(MT.ARG_ROUND_IDX) == fresh_tag
        assert m.get(MT.ARG_CLIENT_INDEX) == fresh_client
    # the worker's re-upload of the outstanding assignment is accepted once
    upload(fresh_tag)
    assert len(server._buffer) == 2
    # ...and the reply to IT is a genuinely new assignment
    assert sent[-1].get(MT.ARG_ROUND_IDX) != fresh_tag


def test_async_requires_buffer_k():
    import pytest

    from fedml_tpu.algorithms.fedbuff import FedBuffServerManager
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub

    data = synthetic_classification(
        num_clients=4, num_classes=2, feat_shape=(8,), samples_per_client=8,
    )
    model = create_model("lr", "synthetic", (8,), 2)
    cfg = _cfg(comm_round=1, k=0, workers=2, total=4)
    with pytest.raises(ValueError):
        FedBuffServerManager(cfg, LoopbackCommManager(LoopbackHub(), 0), model, data=data)
