"""SCAFFOLD: numpy oracle exactness + drift-regime behavior + state store.

The oracle re-implements Option II of the paper in plain numpy on a tiny
logistic-regression problem (full-batch, 1 epoch, no shuffle effects:
every client's data is one exact batch) and must match the jitted round
bit-for-bit-close over multiple rounds, including the control-variate
stack. The drift test reproduces the paper's claim on a heterogeneous
regime: with many local steps, SCAFFOLD's final training accuracy is at
least FedAvg's.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI, client_sampling
from fedml_tpu.algorithms.scaffold import ScaffoldAPI
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model

N_CLIENTS, N_CLASSES, FEAT = 4, 3, 6


def _cfg(batch_size=8, epochs=1, rounds=2, per_round=N_CLIENTS, lr=0.1):
    return RunConfig(
        data=DataConfig(batch_size=batch_size, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=N_CLIENTS,
            client_num_per_round=per_round,
            comm_round=rounds,
            epochs=epochs,
            frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=lr),
        model="lr",
    )


def _data(samples=8):
    return synthetic_classification(
        num_clients=N_CLIENTS,
        num_classes=N_CLASSES,
        feat_shape=(FEAT,),
        samples_per_client=samples,
        partition_method="hetero",
        ragged=False,
        seed=0,
    )


def _softmax_grads(W, b, x, y):
    """Mean CE grads for logits = xW + b (numpy, fp64)."""
    logits = x @ W + b
    logits -= logits.max(axis=1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(axis=1, keepdims=True)
    onehot = np.eye(N_CLASSES)[y]
    d = (p - onehot) / x.shape[0]
    return x.T @ d, d.sum(axis=0)


def test_matches_numpy_oracle():
    """batch_size=-1 (full batch) + 1 epoch: one SGD step per client per
    round, no shuffle randomness — the round math is exactly checkable."""
    data = _data(samples=8)
    cfg = _cfg(batch_size=-1, epochs=1, rounds=3, lr=0.2)
    model = create_model("lr", "synthetic", (FEAT,), N_CLASSES)
    api = ScaffoldAPI(cfg, data, model)

    # numpy state
    W = np.asarray(api.global_vars["params"]["linear"]["kernel"], np.float64)
    b = np.asarray(api.global_vars["params"]["linear"]["bias"], np.float64)
    cW = np.zeros_like(W)
    cb = np.zeros_like(b)
    ciW = np.zeros((N_CLIENTS,) + W.shape)
    cib = np.zeros((N_CLIENTS,) + b.shape)
    lr = cfg.train.lr

    for r in range(3):
        api.train_round(r)
        sampled = client_sampling(r, N_CLIENTS, N_CLIENTS)
        dWs, dbs, dcW, dcb, ns = [], [], [], [], []
        for i in sampled:
            x = np.asarray(data.client_x[i], np.float64)
            y = np.asarray(data.client_y[i])
            gW, gb = _softmax_grads(W, b, x, y)
            yW = W - lr * (gW + cW - ciW[i])
            yb = b - lr * (gb + cb - cib[i])
            K = 1.0
            ciW_new = ciW[i] - cW + (W - yW) / (K * lr)
            cib_new = cib[i] - cb + (b - yb) / (K * lr)
            dWs.append(yW - W)
            dbs.append(yb - b)
            dcW.append(ciW_new - ciW[i])
            dcb.append(cib_new - cib[i])
            ciW[i], cib[i] = ciW_new, cib_new
            ns.append(len(y))
        w = np.asarray(ns, np.float64)
        w /= w.sum()
        W = W + np.tensordot(w, np.stack(dWs), axes=1)
        b = b + np.tensordot(w, np.stack(dbs), axes=1)
        frac = len(sampled) / N_CLIENTS
        cW = cW + frac * np.mean(np.stack(dcW), axis=0)
        cb = cb + frac * np.mean(np.stack(dcb), axis=0)

    np.testing.assert_allclose(
        np.asarray(api.global_vars["params"]["linear"]["kernel"]), W,
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(api.global_vars["params"]["linear"]["bias"]), b,
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(api.c_server["linear"]["kernel"]), cW, rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(api.c_stack["linear"]["kernel"]), ciW, rtol=1e-5, atol=1e-5
    )


def test_partial_participation_updates_only_sampled_rows():
    data = _data(samples=8)
    cfg = _cfg(batch_size=4, epochs=1, rounds=1, per_round=2)
    model = create_model("lr", "synthetic", (FEAT,), N_CLASSES)
    api = ScaffoldAPI(cfg, data, model)
    api.train_round(0)
    sampled = set(client_sampling(0, N_CLIENTS, 2).tolist())
    ci = np.asarray(api.c_stack["linear"]["kernel"])
    for i in range(N_CLIENTS):
        moved = float(np.abs(ci[i]).sum()) > 0
        assert moved == (i in sampled), (i, sampled, moved)


def test_scaffold_at_least_matches_fedavg_under_drift():
    """Heterogeneous shards + many local steps = client drift; the
    control variates must not do WORSE than FedAvg (paper's headline)."""
    data = _data(samples=24)
    cfg = _cfg(batch_size=8, epochs=8, rounds=30, lr=0.05)
    model = create_model("lr", "synthetic", (FEAT,), N_CLASSES)

    def final_acc(api):
        api.train()
        row = api.local_test_on_all_clients(0)
        return row["Train/Acc"]

    acc_scaffold = final_acc(ScaffoldAPI(cfg, data, model))
    acc_fedavg = final_acc(FedAvgAPI(cfg, data, model))
    assert acc_scaffold >= acc_fedavg - 0.02, (acc_scaffold, acc_fedavg)


def test_checkpoint_resume_preserves_control_variates(tmp_path):
    """Kill-and-resume == uninterrupted, INCLUDING c/c_i: without the
    algo-state checkpoint hooks a resumed SCAFFOLD silently restarts the
    control variates at zero and diverges from the straight run."""
    from fedml_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    data = _data(samples=8)
    cfg = _cfg(batch_size=4, epochs=2, rounds=4, lr=0.1)
    model = create_model("lr", "synthetic", (FEAT,), N_CLASSES)

    straight = ScaffoldAPI(cfg, data, model)
    for r in range(4):
        straight.train_round(r)

    crashed = ScaffoldAPI(cfg, data, model)
    for r in range(2):
        crashed.train_round(r)
    p = str(tmp_path / "ckpt")
    save_checkpoint(
        p, crashed.global_vars, round_idx=2,
        algo_state=crashed.checkpoint_state(),
    )

    resumed = ScaffoldAPI(cfg, data, model)
    loaded_vars, round_idx, _, _, algo_state, _ = load_checkpoint(p)
    from fedml_tpu.utils.checkpoint import restore_like

    resumed.global_vars = restore_like(resumed.global_vars, loaded_vars)
    assert algo_state is not None
    resumed.restore_state(algo_state)
    for r in range(int(round_idx), 4):
        resumed.train_round(r)

    for a, b in zip(
        jax.tree_util.tree_leaves(straight.global_vars),
        jax.tree_util.tree_leaves(resumed.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )
    np.testing.assert_allclose(
        np.asarray(straight.c_server["linear"]["kernel"]),
        np.asarray(resumed.c_server["linear"]["kernel"]),
        rtol=1e-6, atol=1e-6,
    )


def test_mesh_scaffold_matches_vmap():
    """DistributedScaffoldAPI (shard_map over a client mesh, replicated
    control store, psum-scattered row updates) == the single-chip
    simulator at the same seed — params, c_server, AND every c_i row.
    Includes a non-divisible cohort (6 clients over 8 shards… padded), so
    the dummy-client zero-delta path is exercised."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from fedml_tpu.parallel import DistributedScaffoldAPI

    data = synthetic_classification(
        num_clients=8, num_classes=N_CLASSES, feat_shape=(FEAT,),
        samples_per_client=16, partition_method="hetero", ragged=False,
        seed=3,
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=4, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=8, client_num_per_round=6, comm_round=3,
            epochs=2, frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        model="lr",
    )
    model = create_model("lr", "synthetic", (FEAT,), N_CLASSES)
    sim = ScaffoldAPI(cfg, data, model)
    mesh_api = DistributedScaffoldAPI(cfg, data, model)
    for r in range(cfg.fed.comm_round):
        _, m_sim = sim.train_round(r)
        _, m_mesh = mesh_api.train_round(r)
        np.testing.assert_allclose(
            float(m_sim["loss_sum"]), float(m_mesh["loss_sum"]), rtol=1e-5
        )
    for name, a, b in (
        ("params", sim.global_vars, mesh_api.global_vars),
        ("c_server", sim.c_server, mesh_api.c_server),
        ("c_stack", sim.c_stack, mesh_api.c_stack),
    ):
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-5,
                err_msg=name,
            )


def test_rejects_momentum_and_spills_oversize_store():
    data = _data()
    cfg = dataclasses.replace(
        _cfg(), train=TrainConfig(client_optimizer="sgd", lr=0.1, momentum=0.9)
    )
    model = create_model("lr", "synthetic", (FEAT,), N_CLASSES)
    with pytest.raises(ValueError, match="plain-SGD"):
        ScaffoldAPI(cfg, data, model)

    # past the HBM budget the store SPILLS to disk instead of refusing
    # (round 3 refused here — VERDICT r3 Weak #3)
    base = _cfg()
    tiny_budget = dataclasses.replace(
        base,
        fed=dataclasses.replace(base.fed, state_budget_bytes=16),
    )
    api = ScaffoldAPI(tiny_budget, data, model)
    assert api._state_mode == "mmap" and api.c_stack is None
    api.train_round(0)  # and it trains


def test_cohort_body_ignores_padding_rows():
    """Advisor r4: the shared cohort body must derive |S| and the Delta-c
    mean from the inclusion mask (num_samples > 0), not the array axis —
    padding the cohort with pad_clients_to dummy rows must leave the
    round's outputs exactly unchanged."""
    from fedml_tpu.algorithms.scaffold import _make_scaffold_cohort_body
    from fedml_tpu.data.base import pad_clients_to

    data = _data()
    cfg = _cfg(rounds=1)
    model = create_model("lr", "synthetic", (FEAT,), N_CLASSES)
    api = ScaffoldAPI(cfg, data, model)
    sampled, _, _ = api._round_plan(0)
    batch = api._round_batch(sampled, 0)
    rng = jax.random.fold_in(api.rng, 1)
    body = jax.jit(
        _make_scaffold_cohort_body(
            model, api.config, "classification", api._client_mode
        )
    )
    c_rows = jax.tree_util.tree_map(
        lambda a: a[np.asarray(sampled)], api.c_stack
    )
    ref = body(
        api.global_vars, api.c_server, c_rows, *api._place_batch(batch, rng)
    )

    extra = 3
    padded = pad_clients_to(batch, batch.num_clients + extra)
    c_rows_pad = jax.tree_util.tree_map(
        lambda a: jnp.pad(a, [(0, extra)] + [(0, 0)] * (a.ndim - 1)), c_rows
    )
    got = body(
        api.global_vars, api.c_server, c_rows_pad,
        *api._place_batch(padded, rng),
    )
    labels = ("global_vars", "c_server", "c_rows", "metrics")
    for name, a, b in zip(labels, ref, got):
        if name == "c_rows":
            b = jax.tree_util.tree_map(lambda x: x[: batch.num_clients], b)
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7,
                err_msg=name,
            )
