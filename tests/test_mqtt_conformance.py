"""MQTT 3.1.1 wire conformance for the from-scratch broker (VERDICT r4
Missing #2 / Next #9): the reference's backend ran against real paho
(mqtt_comm_manager.py:14-123); paho is not installable here (no egress),
so interop is proven at the layer that matters — the WIRE:

1. committed byte-level fixtures (tests/golden/mqtt311_paho_session.json,
   the exact bytes paho-mqtt 1.6.x emits for a canonical session, each
   step citing its normative OASIS spec section) are replayed against a
   live MiniMqttBroker TCP socket and the broker's responses asserted
   byte-for-byte;
2. a FOREIGN wire client — implemented in this file purely from the spec,
   sharing zero code with core/mqtt_broker.py — completes a two-party
   federation against the broker, talking to the in-house
   MqttCommManager on the other side (binary Message envelopes through
   real TCP MQTT).

If paho ever lands in the image, point MqttCommManager at the broker
host/port and it takes the real-paho path automatically
(core/mqtt_comm.py:88-118); these fixtures stay as the regression floor.
"""

import json
import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.mqtt_broker import MiniMqttBroker

GOLDEN = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "golden", "mqtt311_paho_session.json",
)


def _recv_exact(sock, n, timeout=10.0):
    sock.settimeout(timeout)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_packet_bytes(sock):
    """One whole MQTT control packet, raw — reimplemented from MQTT-3.1.1
    §2.2 here (no imports from the broker module: the reader that checks
    the broker must not BE the broker)."""
    head = _recv_exact(sock, 1)
    mult, rl, n = 1, 0, 0
    while True:
        b = _recv_exact(sock, 1)
        head += b
        rl += (b[0] & 0x7F) * mult
        mult *= 128
        n += 1
        if not b[0] & 0x80:
            break
        if n > 4:
            raise ValueError("malformed remaining length")
    return head + (_recv_exact(sock, rl) if rl else b"")


def test_paho_session_fixtures_replay_byte_exact():
    fix = json.load(open(GOLDEN))
    broker = MiniMqttBroker()
    try:
        s = socket.create_connection(("127.0.0.1", broker.port))
        for step in fix["session"]:
            raw = bytes.fromhex(step["hex"])
            if step["dir"] == "c2s":
                s.sendall(raw)
            else:
                got = _recv_packet_bytes(s)
                assert got == raw, (
                    f"{step['name']} ({step['spec']}): broker sent "
                    f"{got.hex()}, spec/paho stream expects {raw.hex()}"
                )
        s.close()
    finally:
        broker.close()


def test_multibyte_remaining_length_roundtrip():
    """§2.2.3: payloads past 127 bytes need the varint continuation bit —
    a framing bug here corrupts every real model exchange (the fixture
    pins 321 -> C1 02)."""
    fix = json.load(open(GOLDEN))["multibyte_remaining_length"]
    topic = fix["publish_topic"]
    payload = bytes(range(256)) * 2
    payload = payload[: fix["payload_len"]]
    body = struct.pack("!H", len(topic)) + topic.encode() + payload
    assert len(body) == 321
    header = bytes.fromhex(fix["header_hex"])

    broker = MiniMqttBroker()
    try:
        sub = socket.create_connection(("127.0.0.1", broker.port))
        sub.sendall(bytes.fromhex("101500044d5154540402003c00097061686f2d74657374"))
        assert _recv_packet_bytes(sub)[:1] == b"\x20"
        tb = struct.pack("!H", len(topic)) + topic.encode()
        sub.sendall(b"\x82" + bytes([2 + len(tb) + 1]) + b"\x00\x01" + tb + b"\x00")
        assert _recv_packet_bytes(sub)[:1] == b"\x90"

        pub = socket.create_connection(("127.0.0.1", broker.port))
        # CONNECT, client-id "pub2": remaining length 10 + (2+4) = 0x10
        pub.sendall(bytes.fromhex("101000044d5154540402003c000470756232"))
        assert _recv_packet_bytes(pub)[:1] == b"\x20"
        pub.sendall(header + body)
        got = _recv_packet_bytes(sub)
        assert got == header + body  # identical multibyte-varint framing back
        pub.close()
        sub.close()
    finally:
        broker.close()


class _ForeignWireClient:
    """Spec-only MQTT 3.1.1 QoS-0 client: hand-rolled frames, zero shared
    code with core/mqtt_broker.MiniMqttClient (different structure on
    purpose — it exists to catch bugs both in-house endpoints would share)."""

    def __init__(self, host, port, client_id, on_message):
        self._sock = socket.create_connection((host, port))
        cid = client_id.encode()
        var = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack("!H", len(cid)) + cid
        self._sock.sendall(b"\x10" + self._varint(len(var)) + var)
        ack = _recv_packet_bytes(self._sock)
        assert ack == b"\x20\x02\x00\x00", ack.hex()
        self._on_message = on_message
        self._pid = 0
        threading.Thread(target=self._reader, daemon=True).start()

    @staticmethod
    def _varint(n):
        out = bytearray()
        while True:
            d = n % 128
            n //= 128
            out.append(d | (0x80 if n else 0))
            if not n:
                return bytes(out)

    def subscribe(self, topic):
        self._pid += 1
        t = topic.encode()
        body = (
            struct.pack("!H", self._pid)
            + struct.pack("!H", len(t)) + t + b"\x00"
        )
        self._sock.sendall(b"\x82" + self._varint(len(body)) + body)

    def publish(self, topic, payload):
        t = topic.encode()
        body = struct.pack("!H", len(t)) + t + bytes(payload)
        self._sock.sendall(b"\x30" + self._varint(len(body)) + body)

    def _reader(self):
        try:
            while True:
                pkt = _recv_packet_bytes(self._sock)
                if pkt[0] >> 4 == 3:  # PUBLISH
                    # re-parse the remaining-length to find the body start
                    i = 1
                    while pkt[i] & 0x80:
                        i += 1
                    body = pkt[i + 1:]
                    tlen = struct.unpack("!H", body[:2])[0]
                    self._on_message(body[2:2 + tlen].decode(), body[2 + tlen:])
        except (ConnectionError, OSError, socket.timeout):
            pass

    def close(self):
        try:
            self._sock.sendall(b"\xe0\x00")
            self._sock.close()
        except OSError:
            pass


def test_foreign_wire_client_federates_with_inhouse_manager():
    """The interop proof: the in-house MqttCommManager (server side) and
    the spec-only foreign client (client side) complete a two-round
    model exchange through the broker over real TCP — binary Message
    envelopes, dtype-exact both ways."""
    from fedml_tpu.core.comm import Observer
    from fedml_tpu.core.message import Message
    from fedml_tpu.core.mqtt_comm import MqttCommManager

    broker = MiniMqttBroker()
    got_server = []

    class _Srv(Observer):
        def receive_message(self, t, m):
            got_server.append(m)

    try:
        server = MqttCommManager(0, host="127.0.0.1", port=broker.port)
        server.add_observer(_Srv())
        rx = threading.Thread(
            target=server.handle_receive_message, daemon=True
        )
        rx.start()

        got_client = []
        client = _ForeignWireClient(
            "127.0.0.1", broker.port, "foreign-client",
            on_message=lambda t, p: got_client.append(
                Message.from_bytes(p)
            ),
        )
        client.subscribe("fedml_tpu/to_1")
        time.sleep(0.2)  # both SUBSCRIBEs in flight before any publish

        w = np.arange(6, dtype=np.float32).reshape(2, 3)
        for rnd in range(2):
            # server -> client: broadcast the "global model"
            m = Message("sync", 0, 1)
            m.add_params("round", rnd)
            m.add_params("w", w * (rnd + 1))
            server.send_message(m)
            deadline = time.time() + 10
            while len(got_client) < rnd + 1 and time.time() < deadline:
                time.sleep(0.01)
            assert len(got_client) == rnd + 1, "client missed the broadcast"
            rx_msg = got_client[-1]
            np.testing.assert_array_equal(rx_msg.get("w"), w * (rnd + 1))

            # client -> server: upload a delta through the FOREIGN stack
            up = Message("upload", 1, 0)
            up.add_params("round", rnd)
            up.add_params("delta", rx_msg.get("w") + 1.0)
            client.publish("fedml_tpu/to_0", up.to_bytes())
            while len(got_server) < rnd + 1 and time.time() < deadline:
                time.sleep(0.01)
            assert len(got_server) == rnd + 1, "server missed the upload"
            np.testing.assert_array_equal(
                got_server[-1].get("delta"), w * (rnd + 1) + 1.0
            )
        client.close()
        server.stop_receive_message()
        rx.join(timeout=5)
    finally:
        broker.close()
