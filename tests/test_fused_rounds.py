"""Fused multi-round scan (FedConfig.fused_rounds): T rounds as one jitted
lax.scan over the HBM data store must reproduce the eager per-round loop —
same sampling (host-side, ref FedAVGAggregator.py:80-88 parity), same PRNG
stream (fold_in(base, r+1) → split), same weighted average."""

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression

NUM_CLIENTS = 10
NUM_CLASSES = 4
FEAT = (6,)


def _data(ragged):
    return synthetic_classification(
        num_clients=NUM_CLIENTS,
        num_classes=NUM_CLASSES,
        feat_shape=FEAT,
        samples_per_client=24,
        partition_method="hetero",
        ragged=ragged,
        seed=11,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=NUM_CLASSES),
        input_shape=FEAT,
        num_classes=NUM_CLASSES,
        name="lr",
    )


def _cfg(fused_rounds, comm_round=8, freq=100):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=4,
            comm_round=comm_round,
            epochs=2,
            frequency_of_the_test=freq,
            fused_rounds=fused_rounds,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1, momentum=0.9),
        seed=3,
    )


@pytest.mark.parametrize("ragged", [False, True])
@pytest.mark.recompile_budget(60)  # standalone worst case ~36; a per-round
# recompile storm across the two 8-round runs would blow well past this
def test_fused_matches_eager(ragged, recompile_sentinel):
    data, model = _data(ragged), _model()
    eager = FedAvgAPI(_cfg(1), data, model)
    assert eager._store is not None, "device store required for this test"
    eager.train()

    fused = FedAvgAPI(_cfg(4), data, model)
    fused.train()
    # identical per-round logged metrics: the mask-aware epoch shuffle makes
    # minibatch composition independent of the chunk-uniform padded capacity,
    # so fused == eager to numerical identity even for ragged clients
    tol = dict(atol=1e-6, rtol=1e-6)
    for re, rf in zip(eager.history, fused.history):
        assert re["round"] == rf["round"]
        np.testing.assert_allclose(re["Train/Loss"], rf["Train/Loss"], **tol)
    for a, b in zip(
        jax.tree_util.tree_leaves(eager.global_vars),
        jax.tree_util.tree_leaves(fused.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **tol)


def test_chunks_respect_eval_rounds():
    """Eval rounds must terminate a chunk so Test/Acc reads the right
    model; eval metrics match the eager run."""
    data, model = _data(False), _model()
    eager = FedAvgAPI(_cfg(1, comm_round=9, freq=3), data, model)
    eager.train()
    fused = FedAvgAPI(_cfg(5, comm_round=9, freq=3), data, model)
    fused.train()
    eval_rounds_e = [r["round"] for r in eager.history if "Test/Acc" in r]
    eval_rounds_f = [r["round"] for r in fused.history if "Test/Acc" in r]
    assert eval_rounds_e == eval_rounds_f
    for re, rf in zip(eager.history, fused.history):
        if "Test/Acc" in re:
            np.testing.assert_allclose(
                re["Test/Acc"], rf["Test/Acc"], atol=1e-6
            )
            np.testing.assert_allclose(
                re["Test/Loss"], rf["Test/Loss"], atol=1e-5
            )


def test_fused_vmap_mode_cuts_chunks_at_class_changes():
    """Under client_parallelism='vmap', padded steps execute real compute,
    so fused chunks must never span a steps-class change (the round-2
    regression); the chunked run still matches eager exactly."""
    import dataclasses

    data, model = _data(True), _model()
    cfg = _cfg(4)
    cfg = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, client_parallelism="vmap")
    )
    eager_cfg = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, fused_rounds=1)
    )
    eager = FedAvgAPI(eager_cfg, data, model)
    eager.train()
    fused = FedAvgAPI(cfg, data, model)
    # every planned chunk stays within one steps class
    r = 0
    while r < cfg.fed.comm_round:
        L = fused._fused_chunk_len(r)
        classes = {fused._round_steps_class(r + off) for off in range(L)}
        assert len(classes) == 1, (r, L, classes)
        r += L
    fused.train()
    for a, b in zip(
        jax.tree_util.tree_leaves(eager.global_vars),
        jax.tree_util.tree_leaves(fused.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6
        )


@pytest.mark.recompile_budget(60)
def test_warmup_pre_enumerates_chunk_programs_beyond_round0(recompile_sentinel):
    """ISSUE-14 satellite (PR-8 leftover): warmup walks the horizon's
    chunk schedule and AOT-compiles every distinct fused program — not
    just round 0's — so later chunks (lengths cut by eval boundaries)
    dispatch warmed executables. Numerics stay byte-identical to the
    unwarmed run."""
    data, model = _data(False), _model()
    # freq=7 cuts chunks at rounds 7/14: lengths beyond round 0's appear
    cfg = _cfg(4, comm_round=20, freq=7)
    warm = FedAvgAPI(cfg, data, _model())
    rows = warm.warmup()
    chunk_rows = [
        k for k in rows
        if k.startswith("compile/round_fused_r") and k.endswith("_compile_s")
    ]
    assert len(chunk_rows) >= 2, rows  # beyond round 0's single chunk
    assert rows.get("compile/warm_chunk_programs", 0) >= 2, rows
    warm.train()

    cold = FedAvgAPI(cfg, data, _model())
    cold.train()
    for a, b in zip(
        jax.tree_util.tree_leaves(warm.global_vars),
        jax.tree_util.tree_leaves(cold.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for rw, rc in zip(warm.history, cold.history):
        assert rw["Train/Loss"] == rc["Train/Loss"]
