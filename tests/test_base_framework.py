"""Base framework templates: central scalar-sum skeleton and serverless
gossip over the loopback transport."""

import threading

import numpy as np

from fedml_tpu.algorithms.base_framework import (
    DecentralizedWorkerManager,
    MSG_GOSSIP,
    run_base_framework,
)
from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
from fedml_tpu.partition.topology import SymmetricTopologyManager


def test_base_framework_sums():
    assert run_base_framework([1.0, 2.5, 3.5]) == 7.0


def test_decentralized_gossip_converges_to_mean():
    N = 4
    topo = SymmetricTopologyManager(N, neighbor_num=N)  # fully connected
    topo.generate_topology()
    hub = LoopbackHub()
    values = [np.array([float(i)]) for i in range(N)]
    workers = [
        DecentralizedWorkerManager(
            LoopbackCommManager(hub, r), r, topo, values[r], rounds=6
        )
        for r in range(N)
    ]
    # run() publishes each worker's round-0 value from its own receive
    # thread (single-threaded state mutation — see DecentralizedWorkerManager)
    threads = [threading.Thread(target=w.run, daemon=True) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    mean = np.mean([float(i) for i in range(N)])
    for w in workers:
        np.testing.assert_allclose(w.value, mean, atol=1e-6)
