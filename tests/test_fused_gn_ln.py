"""Fused (custom-VJP) GroupNorm / LayerNorm: exactness vs flax modules.

Same contract as tests/test_fused_bn.py: forward parity with
nn.GroupNorm/nn.LayerNorm (fp32 stats), gradient parity (dx, dgamma,
dbeta incl. the μ/σ² terms) against AD of an unfused reference, identical
param trees under the FEDML_TPU_FUSED_NORMS A/B switch.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops.fused_groupnorm import gn_act, ln_act

EPS = 1e-6


def _ref_gn(x, gamma, beta, gs, relu=False):
    x32 = x.astype(jnp.float32)
    N, C = x.shape[0], x.shape[-1]
    G = C // gs
    xg = x32.reshape(N, -1, G, gs)
    mean = jnp.mean(xg, axis=(1, 3), keepdims=True)
    var = jnp.mean(xg * xg, axis=(1, 3), keepdims=True) - mean**2
    xhat = ((xg - mean) * jax.lax.rsqrt(var + EPS)).reshape(x.shape)
    y = xhat * gamma + beta
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


def _ref_ln(x, gamma, beta, relu=False):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True) - mean**2
    y = (x32 - mean) * jax.lax.rsqrt(var + EPS) * gamma + beta
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("relu", [False, True])
def test_gn_act_matches_reference_and_grads(dtype, relu):
    k = jax.random.PRNGKey(0)
    gs = 4
    x = jax.random.normal(k, (3, 5, 5, 8), dtype)
    gamma = jax.random.normal(jax.random.fold_in(k, 1), (8,)) * 0.5 + 1.0
    beta = jax.random.normal(jax.random.fold_in(k, 2), (8,)) * 0.1
    ct = jax.random.normal(jax.random.fold_in(k, 3), x.shape, dtype)

    y = gn_act(x, gamma, beta, gs, EPS, relu)
    rtol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(_ref_gn(x, gamma, beta, gs, relu), np.float32),
        rtol=rtol, atol=1e-5,
    )

    def loss_f(x, g, b):
        return jnp.sum(
            gn_act(x, g, b, gs, EPS, relu).astype(jnp.float32)
            * ct.astype(jnp.float32)
        )

    def loss_r(x, g, b):
        return jnp.sum(
            _ref_gn(x, g, b, gs, relu).astype(jnp.float32)
            * ct.astype(jnp.float32)
        )

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, gamma, beta)
    tol = 5e-2 if dtype == jnp.bfloat16 else 1e-4
    for a, b, nm in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=tol, atol=tol, err_msg=nm,
        )


def test_gn_matches_flax_groupnorm():
    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (2, 4, 4, 12), jnp.float32)
    ours = gn_act(x, jnp.ones((12,)), jnp.zeros((12,)), 3, EPS, False)
    flax_gn = nn.GroupNorm(num_groups=None, group_size=3, epsilon=EPS)
    v = flax_gn.init(k, x)
    theirs = flax_gn.apply(v, x)
    np.testing.assert_allclose(
        np.asarray(ours), np.asarray(theirs), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ln_act_matches_reference_and_grads(dtype):
    k = jax.random.PRNGKey(2)
    x = jax.random.normal(k, (4, 7, 16), dtype)
    gamma = jax.random.normal(jax.random.fold_in(k, 1), (16,)) * 0.5 + 1.0
    beta = jax.random.normal(jax.random.fold_in(k, 2), (16,)) * 0.1
    ct = jax.random.normal(jax.random.fold_in(k, 3), x.shape, dtype)

    y = ln_act(x, gamma, beta, EPS, False)
    rtol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(_ref_ln(x, gamma, beta), np.float32),
        rtol=rtol, atol=1e-5,
    )

    def loss_f(x, g, b):
        return jnp.sum(
            ln_act(x, g, b, EPS, False).astype(jnp.float32)
            * ct.astype(jnp.float32)
        )

    def loss_r(x, g, b):
        return jnp.sum(
            _ref_ln(x, g, b).astype(jnp.float32) * ct.astype(jnp.float32)
        )

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, gamma, beta)
    tol = 5e-2 if dtype == jnp.bfloat16 else 1e-4
    for a, b, nm in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=tol, atol=tol, err_msg=nm,
        )


def test_ln_matches_flax_layernorm():
    k = jax.random.PRNGKey(3)
    x = jax.random.normal(k, (5, 9), jnp.float32)
    ours = ln_act(x, jnp.ones((9,)), jnp.zeros((9,)), EPS, False)
    flax_ln = nn.LayerNorm(epsilon=EPS)
    v = flax_ln.init(k, x)
    theirs = flax_ln.apply(v, x)
    np.testing.assert_allclose(
        np.asarray(ours), np.asarray(theirs), rtol=1e-5, atol=1e-5
    )


def test_unnamed_gn_ln_trees_identical_under_ab_switch(monkeypatch):
    from fedml_tpu.models.norms import fp32_group_norm, fp32_layer_norm

    class Body(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = fp32_group_norm(2)(x)
            return fp32_layer_norm()(h)

    trees = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("FEDML_TPU_FUSED_NORMS", flag)
        v = Body().init(jax.random.PRNGKey(0), jnp.zeros((2, 3, 4)))
        trees[flag] = jax.tree_util.tree_structure(v)
    assert trees["1"] == trees["0"]


def test_resnet_gn_and_transformer_still_train():
    """Smoke: the GN ResNet and the transformer LM train one step with the
    fused norms on (default) — wiring, shapes, grads all live."""
    from fedml_tpu.config import TrainConfig
    from fedml_tpu.models import create_model
    from fedml_tpu.train.client import make_local_train

    model = create_model("resnet18_gn", "femnist", (28, 28, 3), 10)
    variables = model.init(jax.random.PRNGKey(0))
    lt = make_local_train(
        model, TrainConfig(client_optimizer="sgd", lr=0.1), epochs=1
    )
    x = jnp.zeros((1, 4, 28, 28, 3))  # [S=1, B=4, feat]
    y = jnp.zeros((1, 4), jnp.int32)
    mask = jnp.ones((1, 4))
    v2, mets = lt(variables, x, y, mask, jax.random.PRNGKey(1))
    assert np.isfinite(float(mets["loss_sum"]))
