"""Centralized data-parallel trainer (ref fedml_experiments/centralized/
main.py:54-67,123 DDP/NCCL path; TPU analog: batch sharded over the mesh,
params replicated, XLA emits the gradient all-reduce).

Asserts (a) it learns, (b) DP over an 8-device mesh matches the single-device
run (the torch-DDP "same math, more devices" contract), (c) the CLI driver
reaches it."""

import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.train.centralized import CentralizedTrainer

NUM_CLASSES = 4
FEAT = (6,)


def _data():
    return synthetic_classification(
        num_clients=6,
        num_classes=NUM_CLASSES,
        feat_shape=FEAT,
        samples_per_client=40,
        partition_method="homo",
        seed=3,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=NUM_CLASSES),
        input_shape=FEAT,
        num_classes=NUM_CLASSES,
        name="lr",
    )


def _config(batch_size=16, epochs=6):
    return RunConfig(
        data=DataConfig(batch_size=batch_size),
        fed=FedConfig(comm_round=epochs, frequency_of_the_test=epochs),
        train=TrainConfig(client_optimizer="sgd", lr=0.3, momentum=0.9),
        model="lr",
        seed=0,
    )


def test_centralized_learns():
    trainer = CentralizedTrainer(_config(), _data(), _model())
    loss0, acc0 = trainer.evaluate()
    row = trainer.train()
    assert row["Test/Acc"] > max(acc0 + 0.2, 0.7)
    assert row["Train/Loss"] < loss0


def test_centralized_dp_matches_single_device():
    import jax
    from fedml_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    data, model = _data(), _model()
    single = CentralizedTrainer(_config(), data, model)
    mesh = make_mesh(8, "batch")
    dp = CentralizedTrainer(_config(), data, model, mesh=mesh)
    for e in range(3):
        row_s = single.train_epoch(e)
        row_dp = dp.train_epoch(e)
        # same permutation, same batches; only the reduction layout differs
        assert row_dp["Train/Loss"] == pytest.approx(
            row_s["Train/Loss"], rel=1e-4
        )
    ps = jax.tree_util.tree_leaves(single.params)
    pd = jax.tree_util.tree_leaves(dp.params)
    for a, b in zip(ps, pd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize("norm_impl", ["flax", "fused"])
def test_sync_batchnorm_under_dp_mesh(norm_impl):
    """The reference needs 457 LoC of sync-BN helpers (batchnorm_utils.py)
    to make multi-GPU BatchNorm see the global batch. Under GSPMD the same
    guarantee is automatic: BN's batch mean is a reduction over a sharded
    axis, so XLA inserts the cross-device collective — batch_stats after a
    DP step over 8 devices equal the single-device stats. Pinned for BOTH
    implementations: flax nn.BatchNorm and the production custom-VJP path
    (models/norms.BatchNorm) — the custom VJP must not break the
    automatic collective insertion."""
    import flax.linen as nn
    import jax

    from fedml_tpu.models.norms import BatchNorm as FusedBN
    from fedml_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")

    class BNNet(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            h = nn.Dense(8, name="fc1")(x)
            if norm_impl == "fused":
                h = FusedBN(
                    use_running_average=not train, momentum=0.9, name="bn"
                )(h)
            else:
                h = nn.BatchNorm(
                    use_running_average=not train, momentum=0.9, name="bn"
                )(h)
            return nn.Dense(NUM_CLASSES, name="fc2")(nn.relu(h))

    model = ModelDef(
        BNNet(), input_shape=FEAT, num_classes=NUM_CLASSES,
        has_batch_stats=True, name="bnnet",
    )
    data = _data()
    single = CentralizedTrainer(_config(), data, model)
    dp = CentralizedTrainer(
        _config(), data, model, mesh=make_mesh(8, "batch")
    )
    for e in range(2):
        single.train_epoch(e)
        dp.train_epoch(e)
    s_stats = jax.tree_util.tree_leaves(single.extra["batch_stats"])
    d_stats = jax.tree_util.tree_leaves(dp.extra["batch_stats"])
    for a, b in zip(s_stats, d_stats):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_centralized_full_batch_and_cli():
    from click.testing import CliRunner
    from fedml_tpu.cli import main

    # full batch (-1) exercises the batch_size == dataset-size path
    trainer = CentralizedTrainer(
        _config(batch_size=-1, epochs=3), _data(), _model()
    )
    row = trainer.train()
    assert np.isfinite(row["Train/Loss"])

    result = CliRunner().invoke(
        main,
        [
            "--algorithm", "centralized",
            "--dataset", "synthetic",
            "--model", "lr",
            "--client_num_in_total", "4",
            "--comm_round", "2",
            "--batch_size", "16",
            "--lr", "0.1",
        ],
    )
    assert result.exit_code == 0, result.output
    assert "Test/Acc" in result.output
