"""Wire-fleet runtime tests (fedml_tpu/fleet/ + the connection-budget
reworks it rides on).

Small-scale tier-1 coverage of the fleet gate's claims: spec
determinism, server-side connection budgets (gRPC stream shed + MQTT
connection cap, both priced on the comm meter), admission-door
refusal/backpressure under churn, straggler reaping, and FaultTrace
record→replay byte parity. The ≥1000-process run is
``@pytest.mark.slow`` (and the ci.sh fleet gate); everything else here
runs whole fleets of ~a dozen OS processes, a few seconds each.

Every fleet binds ``base_port + rank`` for ranks 0..population — tests
use disjoint port ranges so a slow teardown can't collide with the next
fleet.
"""

import json
import os
import socket
import time

import numpy as np
import pytest

from fedml_tpu.core.grpc_comm import GrpcCommManager, executor_workers_for
from fedml_tpu.core.message import Message, MessageType as MT
from fedml_tpu.core.mqtt_broker import MiniMqttBroker, MiniMqttClient
from fedml_tpu.core.retry import RemoteRefusal, RetryPolicy
from fedml_tpu.fleet.client import LiteTrainer
from fedml_tpu.fleet.launcher import FleetLauncher
from fedml_tpu.fleet.spec import FleetSpec
from fedml_tpu.telemetry.comm import get_comm_meter


def _run_fleet(doc: dict, out_dir) -> dict:
    launcher = FleetLauncher(
        FleetSpec(doc), str(out_dir), log_fn=lambda m: None
    )
    return launcher.run()


# ---------------------------------------------------------------------------
# FleetSpec: validation + everything derived is pure in the spec
# ---------------------------------------------------------------------------


def test_spec_rejects_unknown_keys_and_bad_ranges():
    with pytest.raises(ValueError, match="unknown keys"):
        FleetSpec({"population": 4, "max_ilve": 2})
    with pytest.raises(ValueError, match="population"):
        FleetSpec({})
    with pytest.raises(ValueError, match="assignments"):
        FleetSpec({"population": 4, "assignments": [3, 1]})
    with pytest.raises(ValueError, match="algorithm"):
        FleetSpec({"population": 4, "algorithm": "fedprox"})


def test_spec_fedavg_guards():
    # sync fleets are fixed-size: no rolling population, no churn budgets
    with pytest.raises(ValueError, match="population <= max_live"):
        FleetSpec({"population": 10, "max_live": 4, "algorithm": "fedavg"})
    with pytest.raises(ValueError, match="churn"):
        FleetSpec(
            {"population": 4, "algorithm": "fedavg", "assignments": [1, 2]}
        )
    # dropout-capable tiers + sync barrier need a deadline to make progress
    with pytest.raises(ValueError, match="deadline_s"):
        FleetSpec({
            "population": 4,
            "algorithm": "fedavg",
            "tiers": {"lowend_phone": 1.0},
        })
    FleetSpec({
        "population": 4,
        "algorithm": "fedavg",
        "tiers": {"lowend_phone": 1.0},
        "deadline_s": 10.0,
    })  # with a deadline the same spec is valid


def test_spec_derived_values_are_pure_in_seed():
    doc = {"population": 50, "assignments": [1, 3], "seed": 7,
           "tiers": {"midrange_phone": 0.5, "lowend_phone": 0.5}}
    a, b = FleetSpec(doc), FleetSpec(dict(doc))
    assert a.join_order() == b.join_order()
    assert sorted(a.join_order()) == list(range(1, 51))
    budgets = [a.assignment_budget(r) for r in a.client_ranks()]
    assert budgets == [b.assignment_budget(r) for r in b.client_ranks()]
    assert all(1 <= v <= 3 for v in budgets)
    assert a.fault_plan_spec() == b.fault_plan_spec()
    # a different seed reshuffles the waves
    c = FleetSpec({**doc, "seed": 8})
    assert c.join_order() != a.join_order()
    # explicit fault_plan override wins verbatim (the replay hook)
    d = FleetSpec({**doc, "fault_plan": "trace:/tmp/t.json"})
    assert d.fault_plan_spec() == "trace:/tmp/t.json"
    # no tiers, no override -> no plan
    assert FleetSpec({"population": 3}).fault_plan_spec() == ""
    # to_json() round-trips through the validator
    e = FleetSpec(a.to_json())
    assert e.join_order() == a.join_order()
    assert e.fault_plan_spec() == a.fault_plan_spec()


def test_spec_from_spec_inline_and_file(tmp_path):
    inline = FleetSpec.from_spec('{"population": 9, "rounds": 3}')
    assert inline.population == 9 and inline.rounds == 3
    p = tmp_path / "fleet.json"
    p.write_text(json.dumps(inline.to_json()))
    assert FleetSpec.from_spec(str(p)).population == 9
    with pytest.raises(ValueError, match="neither inline JSON"):
        FleetSpec.from_spec("/does/not/exist.json")


# ---------------------------------------------------------------------------
# server-side connection budgets
# ---------------------------------------------------------------------------


def test_executor_workers_for_sizing():
    # explicit config wins over any cohort size
    assert executor_workers_for(16, 5000) == 16
    # auto: ~1 thread per 8 peers, floored at 8, capped at 64
    assert executor_workers_for(0, 1) == 8
    assert executor_workers_for(0, 64) == 8
    assert executor_workers_for(0, 256) == 32
    assert executor_workers_for(0, 100000) == 64


def test_grpc_stream_budget_sheds_and_releases():
    """Over-budget inbound RPCs are refused with RESOURCE_EXHAUSTED, the
    client sees RemoteRefusal (so the retry layer redials), both sides
    meter it, and draining the queue releases the backpressure."""
    base = 19580
    server = GrpcCommManager(
        0, {0: "127.0.0.1", 1: "127.0.0.1"}, base_port=base,
        stream_budget=1, expected_peers=2,
    )
    client = GrpcCommManager(
        1, {0: "127.0.0.1", 1: "127.0.0.1"}, base_port=base,
        send_timeout_s=5.0, expected_peers=2,
    )
    client.set_retry_policy(
        RetryPolicy(max_attempts=2, backoff_base_s=0.01, backoff_max_s=0.02)
    )
    meter = get_comm_meter()
    before = meter.snapshot()
    try:
        # server is NOT draining: first message fills the 1-slot budget
        client.send_message(Message(MT.C2S_JOIN, 1, 0))
        with pytest.raises(RemoteRefusal):
            client.send_message(Message(MT.C2S_JOIN, 1, 0))
        after = meter.snapshot()
        shed = after["refused"].get("grpc_stream", 0) - before[
            "refused"
        ].get("grpc_stream", 0)
        redials = after["send_refused"].get(
            str(MT.C2S_JOIN), 0
        ) - before["send_refused"].get(str(MT.C2S_JOIN), 0)
        assert shed >= 2  # both attempts of the refused send were shed
        assert redials >= 2
        server._q.get_nowait()  # drain -> budget frees
        client.send_message(Message(MT.C2S_JOIN, 1, 0))
        assert server._q.qsize() == 1
    finally:
        client.stop_receive_message()
        server.stop_receive_message()


def test_retry_send_burns_one_wait_window_per_unanswered_peer():
    """A peer that never answers costs the sender at most ONE
    wait-for-bind window: the first attempt may wait ``send_timeout_s``
    for the peer's server to bind, but every retry must fail fast so a
    dead JOIN peer can't hold the fleet server's single drain thread for
    attempts x timeout (the churn-stall regression: ~2 minutes per dead
    peer parked the whole fleet)."""
    comm = GrpcCommManager(
        0, {0: "127.0.0.1", 7: "127.0.0.1"}, base_port=19590,
        send_timeout_s=1.0, expected_peers=2,
    )
    comm.set_retry_policy(
        RetryPolicy(max_attempts=4, backoff_base_s=0.01, backoff_max_s=0.05)
    )
    t0 = time.perf_counter()
    try:
        with pytest.raises(Exception):
            # rank 7 never binds 19597: attempt 1 waits the 1 s window,
            # attempts 2-4 must see instant connection-refused
            comm.send_message(Message(MT.C2S_JOIN, 0, 7))
        elapsed = time.perf_counter() - t0
        assert elapsed < 3.0, (
            f"retries waited full windows ({elapsed:.1f}s) — expected one "
            "1 s wait window plus fast-fail redials"
        )
    finally:
        comm.stop_receive_message()


def test_mqtt_connection_cap_refuses_then_admits():
    """Past max_connections a dialer gets CONNACK rc=3 -> RemoteRefusal
    (metered as refused["mqtt_conn"]); dropping a connection frees the
    slot for the next dialer."""
    broker = MiniMqttBroker(max_connections=1)
    before = get_comm_meter().snapshot()["refused"].get("mqtt_conn", 0)
    c1 = c3 = None
    try:
        c1 = MiniMqttClient(
            "127.0.0.1", broker.port, "c1", lambda t, p: None
        )
        with pytest.raises(RemoteRefusal):
            MiniMqttClient("127.0.0.1", broker.port, "c2", lambda t, p: None)
        assert broker.refused == 1
        after = get_comm_meter().snapshot()["refused"].get("mqtt_conn", 0)
        assert after - before >= 1
        c1.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:  # the broker reaps the dropped reader asynchronously
                c3 = MiniMqttClient(
                    "127.0.0.1", broker.port, "c3", lambda t, p: None
                )
                break
            except (RemoteRefusal, ConnectionError, socket.error):
                time.sleep(0.05)
        assert c3 is not None, "freed slot was never re-admitted"
    finally:
        for c in (c1, c3):
            if c is not None:
                try:
                    c.close()
                except Exception:  # noqa: BLE001 — teardown best effort
                    pass
        broker.close()


def test_fedbuff_live_count_uses_membership_not_rank_range():
    """Regression: with an external fleet joining in arbitrary rank
    order, the live count must be the _joined SET minus the dead — a
    single high-rank joiner must not inflate it by hundreds of
    phantom low ranks (which refused every later join at the door)."""
    from fedml_tpu.algorithms.fedbuff import FedBuffServerManager

    mgr = object.__new__(FedBuffServerManager)
    mgr._joined = {117, 900, 3}
    mgr._dead_workers = {3}
    assert mgr._live_worker_count() == 2


# ---------------------------------------------------------------------------
# LiteTrainer: real model-shaped uploads, zero jax, pure in (seed, cid, round)
# ---------------------------------------------------------------------------


def test_lite_trainer_deterministic_and_shape_preserving():
    variables = {
        "params": {
            "w": np.ones((4, 3), np.float32),
            "b": np.zeros((3,), np.float32),
        },
        "steps": np.asarray(5, np.int32),
    }
    t1, t2 = LiteTrainer(seed=3), LiteTrainer(seed=3)
    t1.update_dataset(7)
    t2.update_dataset(7)
    out1, n1 = t1.train(2, variables)
    out2, n2 = t2.train(2, variables)
    assert n1 == n2 == 8
    np.testing.assert_array_equal(out1["params"]["w"], out2["params"]["w"])
    np.testing.assert_array_equal(out1["params"]["b"], out2["params"]["b"])
    # int leaves pass through untouched; float leaves actually move
    assert out1["steps"] == 5
    assert not np.array_equal(out1["params"]["w"], variables["params"]["w"])
    assert t1.last_loss == t2.last_loss
    # a different round perturbs differently
    out3, _ = LiteTrainer(seed=3).train(3, variables)
    assert not np.array_equal(out3["params"]["w"], out1["params"]["w"])


# ---------------------------------------------------------------------------
# whole fleets (OS processes over the real gRPC wire)
# ---------------------------------------------------------------------------


def test_fedbuff_churn_fleet_with_door_refusals(tmp_path, monkeypatch):
    """A rolling population against a tenant whose admission cap is
    SMALLER than the launcher's wave width: the door must refuse (priced
    on joins_refused), refused ranks redial and the fleet still runs to
    completion with zero stuck ranks and the thread bound held."""
    from fedml_tpu.fleet import launcher as launcher_mod

    # ranks still mid-redial-backoff when the tenant finishes don't exit
    # on their own until their retry loop drains; don't sit out the full
    # 10 s production grace for them in a tier-1 test
    monkeypatch.setattr(launcher_mod, "_FINISH_GRACE_S", 2.0)
    stats = _run_fleet({
        "population": 8,
        "max_live": 4,
        "max_workers": 2,          # < max_live: forces door refusals
        "rounds": 4,
        "async_buffer_k": 2,
        "assignments": [2, 2],
        # custom no-dropout profile: the slowdown keeps members seated so
        # the 2-seat door refuses structurally, without dropout_p's
        # respawn churn adding runtime variance to a tier-1 test
        "fault_plan": json.dumps({
            "seed": 11,
            "profiles": {"seated": {"slowdown_s": 0.25,
                                    "flaky_upload_p": 0.05}},
            "fleet": {"seated": 1.0},
            "num_clients": 8,
        }, sort_keys=True),
        "send_fault_p": 0.02,
        "seed": 11,
        "base_port": 19500,
        "orphan_deadline_s": 60.0,
        "client_deadline_s": 60.0,
        "run_deadline_s": 120.0,
    }, tmp_path)
    assert stats["ok"], stats
    assert stats["state"] == "done"
    assert stats["server_steps"] == 4
    assert stats["stuck"] == 0 and stats["orphaned"] == 0
    assert stats["errors"] == 0
    # the door actually refused (and the refusals are priced)
    assert stats["joins_refused"] >= 1
    assert stats["finished_early"] >= 1  # the refused children's exit class
    assert stats["joins_accepted"] >= 1 and stats["leaves"] >= 1
    # thread bound ASSERTED: sampled live grpc-comm threads <= executor size
    assert stats["thread_bound_ok"]
    assert stats["grpc_threads_max"] <= stats["grpc_executor_workers"]
    # lowend tier + chaos injected events, merged into the fleet trace
    assert stats["fault_events"] >= 1
    assert os.path.exists(tmp_path / "fault_trace.json")
    assert os.path.exists(tmp_path / "fleet_stats.json")


def test_sync_fleet_trace_record_replay_byte_parity(tmp_path):
    """Record a sync wire fleet's FaultTrace, replay it through
    fault_plan='trace:<path>' on the same spec — the re-recorded trace
    must be byte-identical (scripted faults, no coin flips)."""
    base = {
        "population": 3,
        "algorithm": "fedavg",
        "rounds": 2,
        "seed": 5,
        # slowdown + flaky only: deterministic events with no sync-barrier
        # stalls (dropout would park each round on deadline_s)
        "fault_plan": json.dumps({
            "seed": 5,
            "default": {"slowdown_s": 0.05, "flaky_upload_p": 0.7},
        }, sort_keys=True),
        "run_deadline_s": 120.0,
    }
    rec = _run_fleet({**base, "base_port": 19530}, tmp_path / "record")
    assert rec["ok"], rec
    trace_path = tmp_path / "record" / "fault_trace.json"
    recorded = trace_path.read_bytes()
    assert json.loads(recorded)["clients"], "no fault events recorded"

    rep = _run_fleet({
        **base,
        "base_port": 19545,
        "fault_plan": f"trace:{trace_path}",
    }, tmp_path / "replay")
    assert rep["ok"], rep
    replayed = (tmp_path / "replay" / "fault_trace.json").read_bytes()
    assert replayed == recorded


def test_zombie_client_is_reaped_and_fleet_still_passes(tmp_path, monkeypatch):
    """A client that hangs before joining (FLEET_TEST_HANG_RANKS) must be
    SIGTERMed by the straggler reaper — counted, not fatal: the tenant
    finishes on the remaining clients and the verdict stays ok."""
    from fedml_tpu.fleet import launcher as launcher_mod
    from fedml_tpu.fleet.client import HANG_ENV

    monkeypatch.setenv(HANG_ENV, "4")
    # the 10 s production grace exists for slow-exiting healthy clients;
    # this fleet's 5 healthy ranks exit in milliseconds on FINISH, so the
    # zombie can be collected fast without weakening what is under test
    monkeypatch.setattr(launcher_mod, "_FINISH_GRACE_S", 2.0)
    stats = _run_fleet({
        "population": 6,
        "max_live": 6,
        "rounds": 3,
        "async_buffer_k": 2,
        "assignments": [2, 3],
        "seed": 2,
        "base_port": 19560,
        # generous per-client deadline: the zombie is collected by the
        # post-FINISH grace reap (deterministically AFTER the tenant is
        # done, so it classifies as terminated_late, not an error)
        "client_deadline_s": 60.0,
        "run_deadline_s": 120.0,
    }, tmp_path)
    assert stats["ok"], stats
    assert stats["server_steps"] == 3
    assert stats["reaped"] >= 1
    assert stats["terminated_late"] >= 1
    assert stats["no_result"] >= 1  # the zombie never wrote a result row
    assert stats["errors"] == 0 and stats["stuck"] == 0


@pytest.mark.slow
def test_thousand_process_wire_fleet(tmp_path):
    """The fleet gate at full scale: ≥1000 distinct OS-process gRPC
    clients churn through one tenant to completion. Demand (rounds ×
    buffer_k) is sized so the whole population must cycle: 980 uploads
    from 1000 single-assignment clients, with the last ranks spawning
    while the final waves drain (max_live slack covers the tail)."""
    stats = _run_fleet({
        "population": 1000,
        "max_live": 64,
        "rounds": 245,
        "async_buffer_k": 4,
        "assignments": [1, 1],
        "send_fault_p": 0.02,
        "seed": 0,
        "base_port": 21000,
        "orphan_deadline_s": 120.0,
        "client_deadline_s": 300.0,
        "run_deadline_s": 800.0,
    }, tmp_path)
    assert stats["ok"], stats
    assert stats["spawned"] >= 1000
    assert stats["server_steps"] == 245
    assert stats["stuck"] == 0 and stats["errors"] == 0
    assert stats["orphaned"] == 0
    assert stats["thread_bound_ok"]
    assert stats["joins_accepted"] >= 980
