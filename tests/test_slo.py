"""SLO watchdogs (serve/slo.py): breach detection against the flight
recorder, degraded-not-restarted supervision, spec parsing, and the
serve CLI's --slo_strict exit code."""

import json
import time

import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.serve import FederationServer, RestartPolicy, SloPolicy
from fedml_tpu.serve.slo import SloWatchdog
from fedml_tpu.telemetry.flight import FlightRecorder
from fedml_tpu.telemetry.metrics import MetricsRegistry
from fedml_tpu.telemetry.spans import Tracer


def _data():
    return synthetic_classification(
        num_clients=6, num_classes=3, feat_shape=(10,),
        samples_per_client=24, partition_method="homo", seed=0,
    )


def _model():
    return create_model("lr", "synthetic", (10,), 3)


def _cfg(comm_round=3, **fed_kw):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3,
            comm_round=comm_round, epochs=1, frequency_of_the_test=100,
            **fed_kw,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def _fold(tracer, r, sleep_s=0.0):
    with tracer.span("round", round=r):
        if sleep_s:
            time.sleep(sleep_s)


# ---------------------------------------------------------------------------
# watchdog unit behavior (pure spans, no federation)
# ---------------------------------------------------------------------------


def test_round_s_breach_counts_per_offending_round():
    tracer = Tracer()
    reg = MetricsRegistry()
    flight = FlightRecorder(max_rounds=8, registry=reg)
    flight.attach(tracer)
    wd = SloWatchdog(
        SloPolicy(round_s=0.005), flight, registry=reg, tenant="t"
    )
    _fold(tracer, 0)  # fast round: no breach
    assert not wd.breached
    _fold(tracer, 1, sleep_s=0.02)
    _fold(tracer, 2, sleep_s=0.02)
    assert wd.breached
    assert wd.breach_counts() == {"round_s": 2}
    assert reg.get("fedml_slo_breaches_total").value(slo="round_s") == 2
    row = wd.summary_row()
    assert row["slo/breached"] == 1
    assert row["slo/round_s"] == 2
    assert row["slo/breaches_total"] == 2


def test_p95_and_rate_wait_for_min_samples():
    tracer = Tracer()
    flight = FlightRecorder(max_rounds=16)
    flight.attach(tracer)
    wd = SloWatchdog(
        SloPolicy(p95_round_s=1e-9, min_rounds_per_s=1e12, min_samples=3),
        flight,
        registry=MetricsRegistry(),
    )
    _fold(tracer, 0)
    _fold(tracer, 1)
    assert not wd.breached  # under min_samples, nothing trips yet
    _fold(tracer, 2)
    assert wd.breach_counts().get("p95_round_s", 0) >= 1
    assert wd.breach_counts().get("min_rounds_per_s", 0) >= 1


def test_max_recompiles_breaches_once_at_the_crossing():
    compiles = {"n": 0}
    tracer = Tracer()
    flight = FlightRecorder(max_rounds=8, recompiles_fn=lambda: compiles["n"])
    flight.attach(tracer)
    wd = SloWatchdog(
        SloPolicy(max_recompiles=2), flight, registry=MetricsRegistry()
    )
    compiles["n"] = 2
    _fold(tracer, 0)
    assert not wd.breached  # at the budget, not past it
    compiles["n"] = 3
    _fold(tracer, 1)
    _fold(tracer, 2)  # still over, but already reported
    assert wd.breach_counts() == {"max_recompiles": 1}


def test_straggler_frac_is_a_fleet_fraction_not_per_cohort():
    """Numerator AND denominator are fleet-wide: 2 stragglers in an
    8-client fleet is 0.25 — NOT 2 over the 4-client cohort (0.5),
    which would breach spuriously on any large fleet with small
    cohorts."""

    class FakeHealth:
        def straggler_ids(self):
            return [1, 2]

        def known_client_count(self):
            return 8

    tracer = Tracer()
    flight = FlightRecorder(max_rounds=8, health=FakeHealth())
    flight.attach(tracer)
    wd = SloWatchdog(
        SloPolicy(straggler_frac=0.3), flight, registry=MetricsRegistry()
    )
    with tracer.span("round", round=0):
        with tracer.span("broadcast", round=0, clients=4):
            pass
    assert flight.last()["clients_seen"] == 8
    assert wd.breach_counts() == {}  # 2/8 = 0.25 <= 0.3 (cohort would lie)
    wd2 = SloWatchdog(
        SloPolicy(straggler_frac=0.2), flight, registry=MetricsRegistry()
    )
    with tracer.span("round", round=1):
        pass
    assert wd2.breach_counts() == {"straggler_frac": 1}  # 0.25 > 0.2


def test_policy_spec_parsing_pops_keys():
    spec = {"name": "t", "slo_round_s": 1.5, "slo_max_recompiles": 3,
            "comm_round": 2}
    p = SloPolicy.from_spec(spec)
    assert p == SloPolicy(round_s=1.5, max_recompiles=3)
    assert "slo_round_s" not in spec and "slo_max_recompiles" not in spec
    assert spec["comm_round"] == 2  # non-SLO keys untouched
    assert SloPolicy.from_spec({"name": "t"}) is None


def test_serve_cli_bad_slo_value_is_a_spec_error(tmp_path):
    """A non-numeric slo_* value is a PARSE-TIME misconfigured spec
    (exit 2), like every other spec guard — not a raw traceback."""
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    spec = {"tenants": [{
        "name": "bad_slo", "algorithm": "fedavg", "runtime": "loopback",
        "model": "lr", "dataset": "synthetic", "client_num_in_total": 6,
        "client_num_per_round": 2, "comm_round": 1, "batch_size": 8,
        "slo_round_s": "fast",
    }]}
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec))
    r = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert r.exit_code == 2, (r.exit_code, r.output)
    assert "invalid SLO value" in r.output


# ---------------------------------------------------------------------------
# breach -> degraded, NOT restarted (the supervision contract)
# ---------------------------------------------------------------------------


def test_breach_degrades_supervised_tenant_without_burning_restarts():
    data, model = _data(), _model()
    srv = FederationServer(prom_port=0)
    sup = srv.create_session(
        "slowpoke", _cfg(), data, model,
        restart=RestartPolicy(budget=3, backoff_base_s=0.01),
        slo=SloPolicy(round_s=1e-9),  # every round breaches
    )
    srv.start()
    results = srv.wait()
    assert results["slowpoke"]["ok"], results  # breaches never crash
    assert sup.restarts == 0  # ...and never consume restart budget
    assert sup.health_state == "degraded"
    summary = results["slowpoke"]["summary"]
    assert summary["slo/breached"] == 1
    assert summary["slo/round_s"] >= 1
    assert summary["supervisor/health"] == "degraded"
    assert summary["supervisor/restarts"] == 0
    # degraded shows in /status AND in the tenant-labeled breach counter
    import urllib.request

    st = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{srv.prom_port}/status").read().decode())
    assert st["tenants"]["slowpoke"]["health"] == "degraded"
    assert st["tenants"]["slowpoke"]["restarts"] == 0
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{srv.prom_port}/metrics").read().decode()
    lines = [
        ln for ln in body.splitlines()
        if ln.startswith("fedml_slo_breaches_total{")
        and 'tenant="slowpoke"' in ln
    ]
    assert lines, body[:2000]
    # budget gauge untouched: all 3 restarts still available
    budget = [
        ln for ln in body.splitlines()
        if ln.startswith("fedml_session_restart_budget_remaining{")
        and 'tenant="slowpoke"' in ln
    ]
    assert budget and budget[0].endswith(" 3.0"), budget
    srv.close()


def test_unsupervised_session_health_state_degrades_on_breach():
    from fedml_tpu.serve import FedSession
    from fedml_tpu.telemetry import TelemetryScope

    data, model = _data(), _model()
    s = FedSession(
        _cfg(comm_round=2), data, model, name="plain",
        scope=TelemetryScope(tenant="plain"), slo=SloPolicy(round_s=1e-9),
    )
    s.run()
    assert s.state == "done"
    assert s.slo_breached
    assert s.health_state == "degraded"
    assert s.status()["health"] == "degraded"


def test_session_rejects_non_policy_slo():
    from fedml_tpu.serve import FedSession

    with pytest.raises(ValueError, match="SloPolicy"):
        FedSession(_cfg(), _data(), _model(), slo={"round_s": 1.0})


# ---------------------------------------------------------------------------
# serve CLI: spec keys + --slo_strict exit code
# ---------------------------------------------------------------------------


def _json_line(output):
    for line in output.splitlines():
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no JSON line in {output!r}")


def test_serve_cli_slo_strict_exit_code(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    spec = {"tenants": [{
        "name": "breachy", "algorithm": "fedavg", "runtime": "loopback",
        "model": "lr", "dataset": "synthetic", "client_num_in_total": 6,
        "client_num_per_round": 2, "comm_round": 2, "batch_size": 8,
        "frequency_of_the_test": 100, "slo_round_s": 1e-9,
    }]}
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec))
    # without --slo_strict: exit 0, breaches reported in the JSON output
    r = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert r.exit_code == 0, r.output
    out = _json_line(r.output)
    assert out["breachy"]["ok"]
    assert out["breachy"]["slo/breached"] == 1
    # with --slo_strict: the dedicated exit code 4
    r = CliRunner().invoke(serve_main, ["--spec", str(p), "--slo_strict"])
    assert r.exit_code == 4, r.output
    assert "breachy" in r.output


def test_serve_cli_slo_strict_passes_on_sane_slo(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    spec = {"tenants": [{
        "name": "fine", "algorithm": "fedavg", "runtime": "loopback",
        "model": "lr", "dataset": "synthetic", "client_num_in_total": 6,
        "client_num_per_round": 2, "comm_round": 2, "batch_size": 8,
        "frequency_of_the_test": 100, "slo_round_s": 3600.0,
    }]}
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec))
    r = CliRunner().invoke(serve_main, ["--spec", str(p), "--slo_strict"])
    assert r.exit_code == 0, r.output
    out = _json_line(r.output)
    assert out["fine"]["ok"]
    assert out["fine"]["slo/breached"] == 0
