"""Ditto personalization (algorithms/ditto.py) — per-client personal
models with a proximal pull toward the global model; beyond the
reference's inventory (SURVEY §2b has no personalization algorithm)."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.ditto import (
    DittoAPI,
    make_ditto_personal_train,
)
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.train.client import make_local_train


def _cfg(total, per_round, rounds, lr=0.1, epochs=1, batch=8):
    return RunConfig(
        data=DataConfig(batch_size=batch),
        fed=FedConfig(
            client_num_in_total=total,
            client_num_per_round=per_round,
            comm_round=rounds,
            epochs=epochs,
            frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=lr),
        seed=0,
    )


def test_lambda_zero_equals_plain_local_train():
    """Degenerate-config oracle: at lam=0 the personal step IS plain local
    training — exact equality with make_local_train under the same rng
    (the personal loop mirrors its rng/permutation structure)."""
    model = create_model("lr", "synthetic", (12,), 3)
    cfg = _cfg(4, 2, 1)
    variables = model.init(jax.random.PRNGKey(7))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 12)), jnp.float32)
    y = jnp.asarray(np.random.default_rng(1).integers(0, 3, (2, 8)))
    mask = jnp.ones((2, 8), jnp.float32)
    rng = jax.random.PRNGKey(3)

    personal = make_ditto_personal_train(model, cfg.train, epochs=1, lam=0.0)
    plain = make_local_train(model, cfg.train, epochs=1)
    v_p, _ = personal(variables["params"], variables, x, y, mask, rng)
    v_l, _ = plain(variables, x, y, mask, rng)
    for a, b in zip(
        jax.tree_util.tree_leaves(v_p), jax.tree_util.tree_leaves(v_l)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_larger_lambda_pins_personal_to_reference():
    """The proximal pull bounds how far the personal model can wander from
    the reference: over many local steps, lam=5 (stable: lr*lam < 1) must
    keep v far closer to w than unregularized training drifts. (A huge
    lam at fixed lr is NOT tested — lr*lam > 2 makes the prox
    discretization oscillate, which is a property of SGD, not of Ditto.)"""
    model = create_model("lr", "synthetic", (12,), 3)
    cfg = _cfg(4, 2, 1)
    variables = model.init(jax.random.PRNGKey(7))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 12)), jnp.float32)
    y = jnp.asarray(np.random.default_rng(1).integers(0, 3, (2, 8)))
    mask = jnp.ones((2, 8), jnp.float32)
    rng = jax.random.PRNGKey(3)

    def drift(lam):
        fn = jax.jit(
            make_ditto_personal_train(model, cfg.train, epochs=10, lam=lam)
        )
        v, _ = fn(variables["params"], variables, x, y, mask, rng)
        return sum(
            float(jnp.sum((a - b) ** 2))
            for a, b in zip(
                jax.tree_util.tree_leaves(v["params"]),
                jax.tree_util.tree_leaves(variables["params"]),
            )
        )

    assert drift(5.0) < drift(0.0) * 0.5


def _conflicting_label_data(num_clients=6, n=60, feat=10, classes=5, seed=0):
    """Clients agree on features but DISAGREE on labels: client k's labels
    are shifted by k mod classes — a single global model cannot fit all
    clients, personal models can. The regime where personalization wins."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(feat, classes))
    client_x, client_y = [], []
    for k in range(num_clients):
        x = rng.normal(size=(n, feat)).astype(np.float32)
        base = np.argmax(x @ w, axis=1)
        client_x.append(x)
        client_y.append(((base + k) % classes).astype(np.int32))
    return FederatedDataset(
        name="conflict",
        client_x=client_x,
        client_y=client_y,
        test_x=client_x[0],
        test_y=client_y[0],
        num_classes=classes,
    )


def test_personalization_beats_global_under_label_conflict():
    data = _conflicting_label_data()
    model = create_model("lr", "synthetic", (10,), 5)
    api = DittoAPI(
        _cfg(6, 6, 20, lr=0.2, epochs=2), data, model, lam=0.1,
    )
    for r in range(20):
        api.train_round(r)
    rows = api.personalized_test_on_clients()
    # global model is torn between conflicting label maps (~1/5 chance);
    # each personal model fits its own map
    assert rows["Personalized/Acc"] > 0.9, rows
    assert rows["Personalized/Acc"] > rows["Global/Acc"] + 0.3, rows


def test_unsampled_rows_untouched():
    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(6,), samples_per_client=16,
        partition_method="homo", seed=0,
    )
    model = create_model("lr", "synthetic", (6,), 3)
    api = DittoAPI(_cfg(8, 2, 1), data, model, lam=0.5)
    before = jax.device_get(api.v_stack)
    sampled, _ = api.train_round(0)
    after = jax.device_get(api.v_stack)
    untouched = sorted(set(range(8)) - set(int(s) for s in sampled))
    assert untouched
    for leaf_b, leaf_a in zip(
        jax.tree_util.tree_leaves(before), jax.tree_util.tree_leaves(after)
    ):
        np.testing.assert_array_equal(leaf_b[untouched], leaf_a[untouched])
        assert not np.array_equal(
            leaf_b[list(sampled)], leaf_a[list(sampled)]
        )


def test_checkpoint_roundtrip_preserves_personal_models():
    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(6,), samples_per_client=16,
        partition_method="homo", seed=0,
    )
    model = create_model("lr", "synthetic", (6,), 3)
    api = DittoAPI(_cfg(4, 2, 1), data, model, lam=0.5)
    api.train_round(0)
    state = jax.device_get(api.checkpoint_state())
    api2 = DittoAPI(_cfg(4, 2, 1), data, model, lam=0.5)
    api2.restore_state(state)
    for a, b in zip(
        jax.tree_util.tree_leaves(api.v_stack),
        jax.tree_util.tree_leaves(api2.v_stack),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_all_padding_client_is_exact_noop_even_with_prox():
    """The sharded round's dummy (padding) clients point at client 0's
    personal row and rely on their delta being EXACTLY zero. The prox term
    lam*(v - w) is nonzero whenever v != w — but the local-train step
    where-gates its ENTIRE update on has_data, so an all-padding client
    must not move at all. Pinned here so a future change to the gating
    cannot silently corrupt row 0 under mesh padding."""
    model = create_model("lr", "synthetic", (6,), 3)
    tc = TrainConfig(client_optimizer="sgd", lr=0.1)
    w = model.init(jax.random.PRNGKey(0))
    v = model.init(jax.random.PRNGKey(1))  # v != w: prox gradient nonzero
    fn = make_ditto_personal_train(model, tc, epochs=2, lam=5.0)
    x = jnp.zeros((2, 4, 6))
    y = jnp.zeros((2, 4), jnp.int32)
    mask = jnp.zeros((2, 4), jnp.float32)  # ALL padding
    v2, _ = fn(w["params"], v, x, y, mask, jax.random.PRNGKey(2))
    for a, b in zip(
        jax.tree_util.tree_leaves(v2), jax.tree_util.tree_leaves(v)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_mesh_ditto_matches_vmap():
    """DistributedDittoAPI (shard_map over a client mesh, replicated
    personal store, all_gathered row deltas) == the single-chip simulator
    at the same seed — global params AND every personal row. Uses a
    non-divisible cohort (6 clients over 8 shards, padded), so the
    dummy-client zero-delta path is exercised."""
    import pytest

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from fedml_tpu.parallel import DistributedDittoAPI

    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(6,), samples_per_client=16,
        partition_method="hetero", ragged=False, seed=3,
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=4, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=8, client_num_per_round=6, comm_round=3,
            epochs=2, frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        model="lr",
    )
    model = create_model("lr", "synthetic", (6,), 3)
    sim = DittoAPI(cfg, data, model, lam=0.3)
    mesh_api = DistributedDittoAPI(cfg, data, model, lam=0.3)
    for r in range(cfg.fed.comm_round):
        _, m_sim = sim.train_round(r)
        _, m_mesh = mesh_api.train_round(r)
        np.testing.assert_allclose(
            float(m_sim["loss_sum"]), float(m_mesh["loss_sum"]), rtol=1e-5
        )
    for name, a, b in (
        ("params", sim.global_vars, mesh_api.global_vars),
        ("v_stack", sim.v_stack, mesh_api.v_stack),
    ):
        for x_, y_ in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            np.testing.assert_allclose(
                np.asarray(x_), np.asarray(y_), rtol=1e-5, atol=1e-5,
                err_msg=name,
            )


def test_cli_ditto_reachable():
    import json

    from click.testing import CliRunner

    from fedml_tpu.cli import main

    result = CliRunner().invoke(
        main,
        [
            "--algorithm", "ditto", "--dataset", "synthetic", "--model", "lr",
            "--client_num_in_total", "4", "--client_num_per_round", "2",
            "--comm_round", "2", "--batch_size", "8", "--lr", "0.1",
            "--ditto_lambda", "0.2",
        ],
    )
    assert result.exit_code == 0, result.output
    row = json.loads(result.output.strip().splitlines()[-1])
    assert "Personalized/Acc" in row and "Global/Acc" in row
