"""Analytic FLOPs counter (utils/flops.py) vs hand-computed counts, and the
scan-slope device timer (utils/profiling.py). These utilities back every MFU
number the benchmark publishes (VERDICT r2: XLA's cost model undercounted
8-24x and silently deflated all round-2 MFU claims), so they get oracle
tests of their own."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.utils import profiling
from fedml_tpu.utils.flops import fn_flops


def test_dense_matmul_count():
    a = jnp.zeros((32, 64))
    b = jnp.zeros((64, 128))
    assert fn_flops(jnp.dot, a, b) == 2 * 32 * 64 * 128


def test_batched_dot_general_count():
    a = jnp.zeros((4, 8, 16))
    b = jnp.zeros((4, 16, 32))
    got = fn_flops(lambda x, y: jnp.einsum("bij,bjk->bik", x, y), a, b)
    assert got == 2 * 4 * 8 * 16 * 32


def test_conv_count_nhwc():
    # SAME-padded 3x3 conv: out spatial = in spatial
    x = jnp.zeros((2, 8, 8, 3))
    w = jnp.zeros((3, 3, 3, 16))

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )

    assert fn_flops(conv, x, w) == 2 * 2 * 8 * 8 * 16 * 3 * 3 * 3


def test_grad_includes_backward():
    """The jaxpr of the gradient carries the real backward primitives —
    for y = sum(x @ w), fwd is one matmul and bwd adds the dW matmul (dx
    is not needed: x is not differentiated)."""
    x = jnp.zeros((16, 32))
    w = jnp.zeros((32, 8))

    def loss(w):
        return jnp.sum(x @ w)

    fwd = 2 * 16 * 32 * 8
    got = fn_flops(jax.grad(loss), w)
    # grad-of-matmul w.r.t. w: x^T @ dy — same shape product as fwd
    assert got == 2 * fwd or got == fwd  # value_and_grad may share the fwd


def test_scan_multiplies_by_length():
    a = jnp.zeros((8, 8))

    def f(a):
        def body(c, _):
            return c @ a, None

        out, _ = jax.lax.scan(body, a, None, length=10)
        return out

    assert fn_flops(f, a) == 10 * 2 * 8 * 8 * 8


def test_while_counts_once_and_warns():
    def f(x):
        def cond(c):
            return c[0, 0] < 100.0

        def body(c):
            return c @ c

        return jax.lax.while_loop(cond, body, x)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        got = fn_flops(f, jnp.zeros((8, 8)))
    assert got == 2 * 8 * 8 * 8
    assert any("ONE iteration" in str(x.message) for x in w)


def test_cond_takes_max_branch():
    a = jnp.zeros((8, 8))
    b = jnp.zeros((8, 128))

    def f(pred, a, b):
        return jax.lax.cond(
            pred,
            lambda: (a @ a)[0, 0],
            lambda: (b @ b.T)[0, 0],
        )

    got = fn_flops(f, True, a, b)
    assert got == 2 * 8 * 128 * 8  # the bigger branch


def test_vmap_batches_count():
    a = jnp.zeros((5, 8, 16))
    b = jnp.zeros((16, 4))
    got = fn_flops(jax.vmap(lambda x: x @ b), a)
    assert got == 2 * 5 * 8 * 16 * 4


def test_jitted_fn_is_descended_into():
    a = jnp.zeros((8, 8))
    assert fn_flops(jax.jit(lambda x: x @ x), a) == 2 * 8 * 8 * 8


def test_scan_slope_seconds_runs_and_is_positive():
    w = jnp.eye(64)

    def step(c):
        return jnp.tanh(c @ w)

    sec = profiling.scan_slope_seconds(step, jnp.ones((64, 64)), k1=1, k2=8)
    # slope of a tiny op can jitter near zero on a fast backend, but must
    # be finite and not absurd
    assert np.isfinite(sec)
    assert sec < 1.0
