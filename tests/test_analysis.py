"""fedml_tpu/analysis/ — fedlint rules (positive + negative per rule),
suppressions/baseline mechanics, the digest-completeness fuzzer
(including the seeded SCAFFOLD eta_g bug it must detect), and the
runtime recompile sentinel."""

import json
import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.analysis.lint import lint_paths, load_baseline, write_baseline
from fedml_tpu.analysis.rules import PROJECT_RULES, RULES


# ---------------------------------------------------------------------------
# lint harness
# ---------------------------------------------------------------------------


def _lint_snippet(tmp_path, code, rel="fedml_tpu/algorithms/snippet.py", rules=None):
    """Lint one synthetic file at a repo-relative location (the directory
    scoping of the rules keys on path components)."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    return lint_paths([str(tmp_path)], rules=rules, base_dir=str(tmp_path))


def _rules_of(report):
    return [f.rule for f in report.findings]


def test_rule_catalog_complete():
    assert set(RULES) == {
        "uncached-jit", "baked-constant", "host-sync", "nondet-in-trace",
        "repr-in-digest", "o-n-per-round",
    }
    assert set(PROJECT_RULES) == {
        "sent-unhandled", "dead-msg-type", "retry-no-dedupe",
        "reply-closure", "lock-order-cycle", "unlocked-shared-mutation",
        "unscoped-thread",
    }
    # the two registries share one --rule namespace: no collisions
    assert not set(RULES) & set(PROJECT_RULES)


# -- uncached-jit -----------------------------------------------------------


def test_uncached_jit_fires_on_bare_jit(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax

        def make_round(model, config):
            def round_fn(gv, x):
                return gv
            return jax.jit(round_fn)
        """,
    )
    assert _rules_of(report) == ["uncached-jit"]


def test_uncached_jit_fires_on_decorator(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(x):
            return x
        """,
        rules=["uncached-jit"],
    )
    assert _rules_of(report) == ["uncached-jit"]


def test_uncached_jit_silent_on_blessed_idioms(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        from fedml_tpu.compile import get_program_cache

        def make_round(model, config):
            def round_fn(gv, x):
                return gv
            cache = get_program_cache()
            def builder():
                return jax.jit(round_fn)
            if model is None:
                return cache.wrap_uncached("r", jax.jit(round_fn))
            builder2 = lambda: jax.jit(round_fn)
            if config is None:
                return cache.get_or_build("r", {"kind": "r"}, builder2)
            return cache.get_or_build(
                "r", {"kind": "r"}, lambda: jax.jit(round_fn)
            )
        """,
        rules=["uncached-jit"],
    )
    assert report.clean, report.render()


def test_uncached_jit_alias_assignment_not_misreported_as_decorator(tmp_path):
    # `jit = jax.jit` is a bare Attribute reference with a non-Call
    # parent — it must not be reported as a "@jax.jit-decorated function"
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        jit = jax.jit
        """,
        rules=["uncached-jit"],
    )
    assert report.clean, report.render()


def test_uncached_jit_out_of_scope_dirs_silent(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        f = jax.jit(lambda x: x)
        """,
        rel="fedml_tpu/utils/snippet.py",
        rules=["uncached-jit"],
    )
    assert report.clean


# -- baked-constant ---------------------------------------------------------


_BAKED_FACTORY = """
    import jax
    from fedml_tpu.compile import get_program_cache

    def make_round(model, config):
        eta_g = config.server.server_lr

        def round_fn(gv, x):
            return gv * eta_g

        return get_program_cache().get_or_build(
            "r",
            {{"kind": "r", "train": config.train, {extra}}},
            lambda: jax.jit(round_fn),
        )
"""


def test_baked_constant_fires_on_undigested_config(tmp_path):
    report = _lint_snippet(
        tmp_path, _BAKED_FACTORY.format(extra=""), rules=["baked-constant"]
    )
    assert _rules_of(report) == ["baked-constant"]
    assert "config.server.server_lr" in report.findings[0].message


def test_baked_constant_silent_when_digested(tmp_path):
    # covering the PREFIX (config.server) covers the leaf read
    report = _lint_snippet(
        tmp_path,
        _BAKED_FACTORY.format(extra='"server": config.server,'),
        rules=["baked-constant"],
    )
    assert report.clean, report.render()


def test_baked_constant_covered_via_local_name(tmp_path):
    # "mode": mode where mode derives from config covers the source path
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        from fedml_tpu.compile import get_program_cache

        def make_round(model, config):
            mode = resolve(config.fed.client_parallelism)

            def round_fn(gv):
                return lift(gv, mode)

            return get_program_cache().get_or_build(
                "r", {"kind": "r", "mode": mode}, lambda: jax.jit(round_fn)
            )
        """,
        rules=["baked-constant"],
    )
    assert report.clean, report.render()


def test_baked_constant_follows_same_module_helper(tmp_path):
    # the scaffold shape: the constant is read in a helper the builder
    # reaches through a bare-config call
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        from fedml_tpu.compile import get_program_cache

        def _body(model, config):
            n = config.fed.client_num_in_total
            def body(gv):
                return gv / n
            return body

        def make_round(model, config):
            body = _body(model, config)
            return get_program_cache().get_or_build(
                "r", {"kind": "r", "train": config.train},
                lambda: jax.jit(body),
            )
        """,
        rules=["baked-constant"],
    )
    assert _rules_of(report) == ["baked-constant"]
    assert "config.fed.client_num_in_total" in report.findings[0].message


# -- host-sync --------------------------------------------------------------


def test_host_sync_fires_inside_traced_body(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        import numpy as np

        def make_round():
            def round_fn(gv, x):
                print(gv)
                h = np.asarray(x)
                return float(h.sum()), gv.item()
            return jax.jit(round_fn)
        """,
        rules=["host-sync"],
    )
    assert sorted(_rules_of(report)) == ["host-sync"] * 4


def test_host_sync_silent_on_host_side_code(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import numpy as np

        def flush_metrics(pending):
            host = np.asarray(pending)
            print(host)
            return float(host.sum())
        """,
        rules=["host-sync"],
    )
    assert report.clean, report.render()


# -- nondet-in-trace --------------------------------------------------------


def test_nondet_fires_inside_traced_body(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax, time, random
        import numpy as np

        def local_train(gv, x):
            jitter = random.random() + time.time()
            noise = np.random.randn(4)
            return gv + jitter + noise
        """,
        rules=["nondet-in-trace"],
    )
    assert sorted(_rules_of(report)) == ["nondet-in-trace"] * 3


def test_nondet_silent_on_host_rng_and_jax_random(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        import numpy as np

        SHUFFLE = np.random.default_rng(0).permutation(8)  # host-side

        def local_train(gv, rng):
            return gv + jax.random.normal(rng, (4,))
        """,
        rules=["nondet-in-trace"],
    )
    assert report.clean, report.render()


# -- repr-in-digest ---------------------------------------------------------


def test_repr_in_digest_fires_in_key_fields_and_fingerprints(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        from fedml_tpu.compile import get_program_cache

        def my_fingerprint(model):
            return {"m": repr(model), "i": id(model)}

        def make_round(model, config, builder):
            return get_program_cache().get_or_build(
                "r", {"kind": "r", "model": repr(model)}, builder
            )
        """,
        rel="fedml_tpu/compile/snippet.py",
        rules=["repr-in-digest"],
    )
    assert sorted(_rules_of(report)) == ["repr-in-digest"] * 3


def test_repr_elsewhere_silent(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        def describe(x):
            return repr(x) + str(id(x))
        """,
        rules=["repr-in-digest"],
    )
    assert report.clean


# -- suppressions + baseline ------------------------------------------------


def test_o_n_per_round_fires_on_population_loop(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        def train_round(self, round_idx):
            for cid in range(self.config.fed.client_num_in_total):
                self.report(cid)
            sums = [w[c] for c in range(config.fed.client_num_in_total)]
            return sums
        """,
    )
    assert _rules_of(report) == ["o-n-per-round", "o-n-per-round"]


def test_o_n_per_round_silent_on_build_time_and_cohort_loops(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        def __init__(self, config):
            # build-time O(N) pass: allowed
            self.counts = [c for c in range(config.fed.client_num_in_total)]

        def make_round(config):
            n_total = config.fed.client_num_in_total
            for i in range(n_total):  # build-time factory: allowed
                pass

        def train_round(self, sampled):
            for cid in sampled:  # cohort loop: allowed
                self.report(cid)
        """,
    )
    assert _rules_of(report) == []


def test_o_n_per_round_out_of_scope_dirs_silent(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        def export(self):
            for cid in range(self.config.fed.client_num_in_total):
                yield cid
        """,
        rel="fedml_tpu/telemetry/snippet.py",
    )
    assert _rules_of(report) == []


def test_justified_suppression_silences_finding(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        f = jax.jit(lambda x: x)  # fedlint: disable=uncached-jit -- probe program
        """,
        rules=["uncached-jit"],
    )
    assert report.clean
    assert len(report.suppressed) == 1


def test_bare_suppression_is_itself_reported(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        f = jax.jit(lambda x: x)  # fedlint: disable=uncached-jit
        """,
        rules=["uncached-jit"],
    )
    assert _rules_of(report) == ["bare-suppression"]


def test_suppression_on_preceding_line(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        # fedlint: disable=uncached-jit -- spans a multi-line call
        f = jax.jit(
            lambda x: x
        )
        """,
        rules=["uncached-jit"],
    )
    assert report.clean and len(report.suppressed) == 1


def test_baseline_roundtrip(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import jax
        f = jax.jit(lambda x: x)
        """,
        rules=["uncached-jit"],
    )
    assert len(report.findings) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), report.findings)
    report2 = lint_paths(
        [str(tmp_path / "fedml_tpu")],
        baseline=load_baseline(str(bl)),
        rules=["uncached-jit"],
        base_dir=str(tmp_path),
    )
    assert report2.clean and len(report2.baselined) == 1
    # fingerprints are line-insensitive: identical content elsewhere in
    # the file must not invalidate the entry
    assert all(
        ":" not in fp.rsplit("::", 1)[-1] or True
        for fp in json.load(open(bl))["findings"]
    )


# ---------------------------------------------------------------------------
# protocol-flow rules (fedml_tpu/analysis/protocol.py)
# ---------------------------------------------------------------------------


_PROTO_PREAMBLE = """
    from fedml_tpu.core.message import Message
    from fedml_tpu.algorithms.base_framework import ClientManager, ServerManager

    class MessageType:
        S2C_PING = "s2c_ping"
        C2S_PONG = "c2s_pong"
"""


def test_sent_unhandled_fires_when_family_never_registers(tmp_path):
    report = _lint_snippet(
        tmp_path,
        _PROTO_PREAMBLE + """
        class PingServerManager(ServerManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def kick(self):
                self.send_message(Message(MessageType.S2C_PING, 0, 1))

        class PingClientManager(ClientManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MessageType.C2S_PONG, self._on_pong
                )

            def _on_pong(self, msg):
                pass
        """,
        rules=["sent-unhandled"],
    )
    assert _rules_of(report) == ["sent-unhandled"]
    assert "S2C_PING" in report.findings[0].message


def test_sent_unhandled_silent_when_peer_registers(tmp_path):
    report = _lint_snippet(
        tmp_path,
        _PROTO_PREAMBLE + """
        class PingServerManager(ServerManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def kick(self):
                self.send_message(Message(MessageType.S2C_PING, 0, 1))

        class PingClientManager(ClientManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MessageType.S2C_PING, self._on_ping
                )

            def _on_ping(self, msg):
                pass
        """,
        rules=["sent-unhandled"],
    )
    assert report.clean, report.render()


def test_sent_unhandled_resolves_type_through_helper_param(tmp_path):
    # the _broadcast_round shape: the type flows through a parameter of
    # a same-class helper; the resolver follows the call site
    report = _lint_snippet(
        tmp_path,
        _PROTO_PREAMBLE + """
        class PingServerManager(ServerManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def kick(self):
                self._fan_out(MessageType.S2C_PING)

            def _fan_out(self, msg_type):
                self.send_message(Message(msg_type, 0, 1))
        """,
        rules=["sent-unhandled"],
    )
    assert _rules_of(report) == ["sent-unhandled"]


def test_dead_msg_type_fires_and_clears_on_send(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        from fedml_tpu.core.message import Message

        class MessageType:
            S2C_LIVE = "s2c_live"
            S2C_ORPHAN = "s2c_orphan"

        def kick(comm):
            comm.send_message(Message(MessageType.S2C_LIVE, 0, 1))
        """,
        rules=["dead-msg-type"],
    )
    assert _rules_of(report) == ["dead-msg-type"]
    assert report.findings[0].scope == "S2C_ORPHAN"


def test_retry_no_dedupe_fires_on_unguarded_accumulation(tmp_path):
    report = _lint_snippet(
        tmp_path,
        _PROTO_PREAMBLE + """
        class UpServerManager(ServerManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)
                self.total = 0

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MessageType.C2S_PONG, self._on_pong
                )

            def _on_pong(self, msg):
                self.total += 1

        class UpClientManager(ClientManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def push(self):
                self.send_message(Message(MessageType.C2S_PONG, 1, 0))
        """,
        rules=["retry-no-dedupe"],
    )
    assert _rules_of(report) == ["retry-no-dedupe"]
    assert report.findings[0].scope == "UpServerManager._on_pong"


def test_retry_no_dedupe_silent_with_tag_guard(tmp_path):
    report = _lint_snippet(
        tmp_path,
        _PROTO_PREAMBLE + """
        class UpServerManager(ServerManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)
                self.total = 0
                self._last = {}

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MessageType.C2S_PONG, self._on_pong
                )

            def _on_pong(self, msg):
                sender = msg.get_sender_id()
                tag = msg.get("tag")
                if self._last.get(sender) == tag:
                    return
                self._last[sender] = tag
                self.total += 1

        class UpClientManager(ClientManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def push(self):
                self.send_message(Message(MessageType.C2S_PONG, 1, 0))
        """,
        rules=["retry-no-dedupe"],
    )
    assert report.clean, report.render()


def test_retry_no_dedupe_silent_on_single_attempt_send(tmp_path):
    # send_message_nowait is the single-attempt path: no retry, no
    # at-least-once hazard, no dedupe requirement on the handler
    report = _lint_snippet(
        tmp_path,
        _PROTO_PREAMBLE + """
        class UpServerManager(ServerManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)
                self.total = 0

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MessageType.C2S_PONG, self._on_pong
                )

            def _on_pong(self, msg):
                self.total += 1

        class UpClientManager(ClientManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def push(self):
                self.comm.send_message_nowait(
                    Message(MessageType.C2S_PONG, 1, 0)
                )
        """,
        rules=["retry-no-dedupe"],
    )
    assert report.clean, report.render()


def test_reply_closure_fires_when_originator_lacks_handler(tmp_path):
    report = _lint_snippet(
        tmp_path,
        _PROTO_PREAMBLE + """
        class QaServerManager(ServerManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MessageType.C2S_PONG, self._on_pong
                )

            def _on_pong(self, msg):
                self.send_message(
                    Message(MessageType.S2C_PING, 0, msg.get_sender_id())
                )

        class QaClientManager(ClientManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def push(self):
                self.send_message(Message(MessageType.C2S_PONG, 1, 0))
        """,
        rules=["reply-closure"],
    )
    assert _rules_of(report) == ["reply-closure"]
    msg = report.findings[0].message
    assert "S2C_PING" in msg and "QaClientManager" in msg


def test_reply_closure_silent_when_originator_handles_reply(tmp_path):
    report = _lint_snippet(
        tmp_path,
        _PROTO_PREAMBLE + """
        class QaServerManager(ServerManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MessageType.C2S_PONG, self._on_pong
                )

            def _on_pong(self, msg):
                self.send_message(
                    Message(MessageType.S2C_PING, 0, msg.get_sender_id())
                )

        class QaClientManager(ClientManager):
            def __init__(self, config, comm, rank):
                super().__init__(config, comm, rank)

            def register_message_receive_handlers(self):
                self.register_message_receive_handler(
                    MessageType.S2C_PING, self._on_ping
                )

            def _on_ping(self, msg):
                pass

            def push(self):
                self.send_message(Message(MessageType.C2S_PONG, 1, 0))
        """,
        rules=["reply-closure"],
    )
    assert report.clean, report.render()


# ---------------------------------------------------------------------------
# concurrency rules (fedml_tpu/analysis/concurrency.py)
# ---------------------------------------------------------------------------


def test_lock_order_cycle_fires_on_inverted_nesting(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import threading

        class Pool:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """,
        rules=["lock-order-cycle"],
    )
    assert _rules_of(report) == ["lock-order-cycle"]
    assert "both orders" in report.findings[0].message


def test_lock_order_cycle_sees_through_call_graph(tmp_path):
    # the second order is transitive: two() holds _b and CALLS a helper
    # that takes _a — the held-call × transitive-acquire edge
    report = _lint_snippet(
        tmp_path,
        """
        import threading

        class Pool:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    self._grab_a()

            def _grab_a(self):
                with self._a:
                    pass
        """,
        rules=["lock-order-cycle"],
    )
    assert _rules_of(report) == ["lock-order-cycle"]


def test_lock_order_consistent_nesting_is_silent(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import threading

        class Pool:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """,
        rules=["lock-order-cycle"],
    )
    assert report.clean, report.render()


def test_unlocked_shared_mutation_fires_on_mixed_discipline(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def reset(self):
                self.n = 0
        """,
        rules=["unlocked-shared-mutation"],
    )
    assert _rules_of(report) == ["unlocked-shared-mutation"]
    assert "reset" in report.findings[0].message


def test_unlocked_shared_mutation_accepts_caller_holds_convention(tmp_path):
    # every intraclass call site of _clear holds the lock: _clear's
    # writes are locked-context, not races
    report = _lint_snippet(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def reset(self):
                with self._lock:
                    self._clear()

            def _clear(self):
                self.n = 0
        """,
        rules=["unlocked-shared-mutation"],
    )
    assert report.clean, report.render()


def test_unlocked_shared_mutation_handles_self_recursion(tmp_path):
    # the secure-agg _complete_round shape: a caller-holds method that
    # re-enters ITSELF — only a greatest fixpoint proves it locked
    report = _lint_snippet(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def flush(self):
                with self._lock:
                    self._drain()

            def _drain(self):
                self.n = 0
                if self.n:
                    self._drain()
        """,
        rules=["unlocked-shared-mutation"],
    )
    assert report.clean, report.render()


def test_unscoped_thread_fires_in_serve_dir(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import threading

        class Runner:
            def start(self):
                t = threading.Thread(target=self.run, daemon=True)
                t.start()
        """,
        rel="fedml_tpu/serve/snippet.py",
        rules=["unscoped-thread"],
    )
    assert _rules_of(report) == ["unscoped-thread"]


def test_unscoped_thread_accepts_scope_wrappers(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import threading
        from fedml_tpu.telemetry import wrap_in_current_scope

        class Runner:
            def start(self):
                threading.Thread(
                    target=wrap_in_current_scope(self.run), daemon=True
                ).start()
                run = self.scope.wrap(self.run)
                threading.Thread(target=run, daemon=True).start()

            def start_inline(self):
                def main():
                    with self.scope.activate():
                        self.run()
                threading.Thread(target=main, daemon=True).start()
        """,
        rel="fedml_tpu/serve/snippet.py",
        rules=["unscoped-thread"],
    )
    assert report.clean, report.render()


def test_unscoped_thread_out_of_scope_dirs_silent(tmp_path):
    report = _lint_snippet(
        tmp_path,
        """
        import threading

        class Runner:
            def start(self):
                threading.Thread(target=self.run, daemon=True).start()
        """,
        rel="fedml_tpu/algorithms/snippet.py",
        rules=["unscoped-thread"],
    )
    assert report.clean, report.render()


# ---------------------------------------------------------------------------
# seeded regressions on REAL tree copies — each rule must detect its
# target bug when the shipped fix/guard is removed
# ---------------------------------------------------------------------------


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _copy_into(tmp_path, rel, source):
    dst = tmp_path / rel
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(source)


def test_seeded_fedbuff_without_leave_dedupe_is_detected(tmp_path):
    """Removing the shipped _on_leave dedupe guard from a copy of the
    real fedbuff module recreates the double-counted-LEAVE bug — the
    rule must catch exactly it (and stay quiet on the intact copy)."""
    repo = _repo_root()
    src = open(os.path.join(repo, "fedml_tpu/algorithms/fedbuff.py")).read()
    msg = open(os.path.join(repo, "fedml_tpu/core/message.py")).read()
    guard = (
        "            if sender in self._dead_workers:\n"
        "                # duplicate LEAVE (at-least-once delivery) — already\n"
        "                # counted; re-adding would double the leaves tally\n"
        "                return\n"
    )
    assert guard in src  # the shipped guard this regression pins
    _copy_into(tmp_path, "fedml_tpu/core/message.py", msg)
    _copy_into(
        tmp_path, "fedml_tpu/algorithms/fedbuff.py", src.replace(guard, "")
    )
    report = lint_paths(
        [str(tmp_path)], rules=["retry-no-dedupe"], base_dir=str(tmp_path)
    )
    assert [f.scope for f in report.findings] == [
        "FedBuffServerManager._on_leave"
    ], report.render()
    # the intact copy is clean — the guard is what the rule keys on
    _copy_into(tmp_path, "fedml_tpu/algorithms/fedbuff.py", src)
    report = lint_paths(
        [str(tmp_path)], rules=["retry-no-dedupe"], base_dir=str(tmp_path)
    )
    assert report.clean, report.render()


def test_seeded_serve_lock_order_inversion_is_detected(tmp_path):
    """The serve layer's real discipline is _admit_lock -> _lock
    (create_session -> _create_session). A method taking them in the
    reverse order, seeded into a copy of the real module, must surface
    as a lock-order-cycle."""
    repo = _repo_root()
    src = open(os.path.join(repo, "fedml_tpu/serve/server.py")).read()
    anchor = "    def add_session("
    assert anchor in src
    inverted = (
        "    def _seeded_inversion(self):\n"
        "        with self._lock:\n"
        "            with self._admit_lock:\n"
        "                pass\n\n"
    )
    _copy_into(
        tmp_path, "fedml_tpu/serve/server.py",
        src.replace(anchor, inverted + anchor, 1),
    )
    report = lint_paths(
        [str(tmp_path)], rules=["lock-order-cycle"], base_dir=str(tmp_path)
    )
    assert _rules_of(report) == ["lock-order-cycle"], report.render()
    assert "_admit_lock" in report.findings[0].message
    # the unmodified copy is clean — the inversion is the bug
    _copy_into(tmp_path, "fedml_tpu/serve/server.py", src)
    report = lint_paths(
        [str(tmp_path)], rules=["lock-order-cycle"], base_dir=str(tmp_path)
    )
    assert report.clean, report.render()


# ---------------------------------------------------------------------------
# walk scope + CLI surface
# ---------------------------------------------------------------------------


def test_lint_walk_visits_every_package_dir():
    """The walk-scope pin: every fedml_tpu/ package directory with .py
    files appears in the visited-file list — a future walk regression
    (pruned dir, bad filter) cannot silently exempt a subsystem."""
    repo = _repo_root()
    pkg = os.path.join(repo, "fedml_tpu")
    report = lint_paths([pkg], base_dir=repo, rules=["repr-in-digest"])
    visited_dirs = {os.path.dirname(p) for p in report.files}
    expected = set()
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        if any(f.endswith(".py") for f in files):
            expected.add(os.path.relpath(root, repo).replace(os.sep, "/"))
    assert visited_dirs == expected
    assert len(report.files) == report.files_checked
    # the subsystems the new rules exist for are in scope
    for sub in ("analysis", "serve", "splitfed", "algorithms", "telemetry"):
        assert f"fedml_tpu/{sub}" in visited_dirs


def _cli_fixture(tmp_path):
    path = tmp_path / "fedml_tpu" / "algorithms" / "snippet.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("import jax\nf = jax.jit(lambda x: x)\n")
    return str(tmp_path / "fedml_tpu")


def test_cli_format_json(tmp_path, capsys):
    from fedml_tpu.analysis.__main__ import main

    rc = main([
        _cli_fixture(tmp_path), "--format", "json",
        "--rule", "uncached-jit",
        "--baseline", str(tmp_path / "no-baseline.json"),
    ])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0  # no --fail-on-findings
    assert [f["rule"] for f in doc["findings"]] == ["uncached-jit"]
    f = doc["findings"][0]
    assert f["path"].endswith("snippet.py") and f["line"] == 2
    assert f["fingerprint"]  # stable CI-artifact identity
    assert doc["files_checked"] == 1 and doc["files"] == [f["path"]]
    assert doc["suppressed"] == 0 and doc["baselined"] == 0


def test_cli_format_text_default_matches_render(tmp_path, capsys):
    from fedml_tpu.analysis.__main__ import main

    target = _cli_fixture(tmp_path)
    baseline = str(tmp_path / "no-baseline.json")
    rc = main([target, "--rule", "uncached-jit", "--baseline", baseline])
    out = capsys.readouterr().out
    assert rc == 0
    # default --format text is exactly LintReport.render() — byte-stable
    # for anything parsing today's output
    assert out.rstrip("\n").endswith(
        "fedlint: 1 finding(s), 0 suppressed, 0 baselined, 1 file(s) checked"
    )
    assert "uncached-jit" in out


def test_cli_fail_on_findings_exit_codes(tmp_path, capsys):
    from fedml_tpu.analysis.__main__ import main

    target = _cli_fixture(tmp_path)
    baseline = str(tmp_path / "no-baseline.json")
    assert main([
        target, "--rule", "uncached-jit", "--baseline", baseline,
        "--fail-on-findings",
    ]) == 1
    capsys.readouterr()


def test_cli_unknown_rule_is_usage_error(tmp_path, capsys):
    from fedml_tpu.analysis.__main__ import main

    rc = main([_cli_fixture(tmp_path), "--rule", "no-such-rule"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "unknown rule" in err and "no-such-rule" in err


def test_cli_list_rules_covers_both_registries(capsys):
    from fedml_tpu.analysis.__main__ import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in list(RULES) + list(PROJECT_RULES):
        assert name in out


# -- the acceptance gate: the shipped tree is clean -------------------------


def test_shipped_tree_has_zero_unsuppressed_findings():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    baseline_path = os.path.join(
        repo, "fedml_tpu", "analysis", "fedlint_baseline.json"
    )
    baseline = load_baseline(baseline_path)
    # the shipped baseline is EMPTY by policy: findings are fixed or
    # suppressed inline with a justification, never silently baselined
    assert baseline == set()
    report = lint_paths(
        [os.path.join(repo, "fedml_tpu")], baseline=baseline, base_dir=repo
    )
    assert report.clean, report.render()
    # the triage actually happened: the suppressions carry justifications
    assert len(report.suppressed) > 0


# ---------------------------------------------------------------------------
# digest-completeness fuzzer
# ---------------------------------------------------------------------------


def _spec(name):
    from fedml_tpu.analysis.digest_audit import default_specs

    return [s for s in default_specs() if s.name == name][0]


def test_digest_audit_all_registered_factories():
    """THE acceptance criterion: every registered program factory's digest
    is complete — no perturbation changes the lowered program without
    changing the digest."""
    from fedml_tpu.analysis.digest_audit import assert_digests_complete

    audits = assert_digests_complete()
    assert len(audits) >= 12
    # the audit exercised real splits, real guards, and benign merges
    statuses = {r.status for a in audits for r in a.results}
    assert {"distinct", "rejected", "merged-identical"} <= statuses


def test_digest_audit_detects_seeded_scaffold_eta_g_bug():
    """Dropping 'server' from the scaffold digest recreates the PR 4 bug
    (eta_g baked into the traced round, digest blind to it) — the fuzzer
    MUST catch it, on exactly the server_lr perturbation."""
    from fedml_tpu.analysis.digest_audit import audit_factory

    audit = audit_factory(
        _spec("scaffold_round"), drop_digest_fields=frozenset({"server"})
    )
    fields = {v.field for v in audit.violations}
    assert "server.server_lr" in fields, audit.render()


def test_digest_audit_detects_dropped_lam_on_ditto():
    """Same hazard class on the PR's own fix: ditto's lam is a baked
    constant; a digest without it must fail the audit."""
    from fedml_tpu.analysis.digest_audit import audit_factory

    audit = audit_factory(
        _spec("ditto_round"), drop_digest_fields=frozenset({"lam"})
    )
    assert any(v.field == "@lam" for v in audit.violations), audit.render()


def test_digest_audit_records_factory_guards_as_rejected():
    from fedml_tpu.analysis.digest_audit import audit_factory

    audit = audit_factory(_spec("scaffold_round"))
    rejected = {r.field for r in audit.results if r.status == "rejected"}
    # SCAFFOLD's plain-SGD guard refuses momentum/adam/prox/wd perturbs
    assert "train.momentum" in rejected and "train.client_optimizer" in rejected
    assert not audit.violations, audit.render()


# ---------------------------------------------------------------------------
# runtime recompile sentinel
# ---------------------------------------------------------------------------


def _force_backend_compile():
    # a fresh jit object + a fresh shape → a guaranteed trace + compile
    n = _force_backend_compile.n = getattr(_force_backend_compile, "n", 100) + 1
    return jax.jit(lambda x: x * 2 + n)(jnp.ones((n,))).block_until_ready()


def test_sentinel_counts_forced_compiles():
    from fedml_tpu.analysis.sentinel import RecompileSentinel

    s = RecompileSentinel(budget=None, label="t").start()
    _force_backend_compile()
    s.stop()
    assert s.recompiles() >= 1
    assert not s.exceeded()  # no budget → never exceeded
    row = s.summary_row()
    assert row["compile/recompiles"] == s.recompiles()
    assert "compile/recompile_budget" not in row


def test_sentinel_budget_zero_fails_on_extra_compile():
    """The seeded-bug case for the sentinel: a forced extra compile under
    budget 0 must raise — this is exactly what the pytest marker's
    fixture turns into a test failure."""
    from fedml_tpu.analysis.sentinel import (
        RecompileBudgetExceeded,
        RecompileSentinel,
        watch_recompiles,
    )

    s = RecompileSentinel(budget=0, label="t").start()
    _force_backend_compile()
    s.stop()
    assert s.exceeded()
    with pytest.raises(RecompileBudgetExceeded, match="XLA compile"):
        s.check()
    assert s.summary_row()["compile/recompile_budget"] == 0

    with pytest.raises(RecompileBudgetExceeded):
        with watch_recompiles(budget=0, label="region"):
            _force_backend_compile()


def test_sentinel_within_budget_is_silent():
    from fedml_tpu.analysis.sentinel import watch_recompiles

    with watch_recompiles(budget=50, label="region") as s:
        _force_backend_compile()
    assert 1 <= s.recompiles() <= 50


def test_sentinel_never_masks_body_exception():
    from fedml_tpu.analysis.sentinel import watch_recompiles

    with pytest.raises(ValueError, match="body"):
        with watch_recompiles(budget=0, label="region"):
            _force_backend_compile()
            raise ValueError("body failure wins")


def test_sentinel_records_program_cache_events(program_cache):
    from fedml_tpu.analysis.sentinel import RecompileSentinel
    from fedml_tpu.compile import ProgramCache, use_program_cache

    with use_program_cache(ProgramCache()) as cache:
        # the sentinel attaches to the cache current at start()
        s = RecompileSentinel(budget=None, label="t").start()
        cache.get_or_build(
            "probe", {"kind": "probe-sentinel"}, lambda: jax.jit(lambda x: x)
        )
        cache.wrap_uncached("opaque", jax.jit(lambda x: x))
        s.stop()
    kinds = [k for k, _ in s.events()]
    assert "build" in kinds and "bypass" in kinds


def test_sentinel_fallback_count_excludes_bypasses():
    """Without jax.monitoring the sentinel counts ProgramCache events —
    but only build/aot_compile: wrap_uncached wrappers compile nothing
    and must not consume a --recompile_budget."""
    from fedml_tpu.analysis.sentinel import RecompileSentinel
    from fedml_tpu.compile import ProgramCache, use_program_cache

    with use_program_cache(ProgramCache()) as cache:
        s = RecompileSentinel(budget=1, label="t").start()
        s._have_monitoring = False  # simulate a jaxlib without monitoring
        cache.get_or_build(
            "probe", {"kind": "probe-fallback"}, lambda: jax.jit(lambda x: x)
        )
        cache.wrap_uncached("opaque1", jax.jit(lambda x: x))
        cache.wrap_uncached("opaque2", jax.jit(lambda x: x))
        s.stop()
    assert s.recompiles() == 1  # one build; two bypasses don't count
    assert not s.exceeded()
    assert s.summary_row()["compile/program_bypasses"] == 2


def test_recompile_sentinel_fixture_observes(recompile_sentinel):
    # unmarked use: pure observation, never fails the test
    _force_backend_compile()
    assert recompile_sentinel.recompiles() >= 0


# ---------------------------------------------------------------------------
# compile-layer introspection hooks + Prometheus export
# ---------------------------------------------------------------------------


def test_program_cache_records_key_fields_and_iterates():
    from fedml_tpu.compile import ProgramCache, use_program_cache

    with use_program_cache(ProgramCache()) as cache:
        prog = cache.get_or_build(
            "probe", {"kind": "probe-fields", "lr": 0.1},
            lambda: jax.jit(lambda x: x),
        )
        assert prog.key_fields == {"kind": "probe-fields", "lr": 0.1}
        assert prog in cache.iter_programs()


def test_use_program_cache_restores_global():
    from fedml_tpu.compile import (
        ProgramCache,
        get_program_cache,
        use_program_cache,
    )

    before = get_program_cache()
    with use_program_cache(ProgramCache()) as fresh:
        assert get_program_cache() is fresh
    assert get_program_cache() is before


def test_compile_gauges_land_in_prometheus_registry():
    from fedml_tpu.compile import ProgramCache, use_program_cache
    from fedml_tpu.telemetry import get_registry

    with use_program_cache(ProgramCache()) as cache:
        cache.get_or_build(
            "probe", {"kind": "probe-prom"}, lambda: jax.jit(lambda x: x)
        )
    text = get_registry().render()
    assert "fedml_compile_cache_misses" in text
    assert "fedml_compile_cache_programs" in text


def test_backend_compile_gauge_exported():
    from fedml_tpu.analysis.sentinel import ensure_backend_listener
    from fedml_tpu.telemetry import get_registry

    assert ensure_backend_listener()
    _force_backend_compile()
    text = get_registry().render()
    assert "fedml_compile_backend_compiles" in text


# ---------------------------------------------------------------------------
# digest fuzzer: auto-derived perturbation lists (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


def test_auto_perturbations_cover_every_runconfig_leaf():
    """Every leaf of the RunConfig dataclass tree is perturbed — either in
    the full per-factory fan-out or in the representative-spec benign
    list. A NEW config knob (e.g. the CompileConfig fields this PR adds)
    is therefore audited by default, with no list to edit."""
    from fedml_tpu.analysis.digest_audit import (
        auto_perturbations,
        runconfig_leaves,
    )

    fanout, benign = auto_perturbations()
    covered = {p.field for p in fanout} | {p.field for p in benign}
    leaves = {path for path, _ in runconfig_leaves()}
    assert covered == leaves
    # the zero-cold-start knobs land in the audit automatically
    assert "compile.executable_cache" in covered
    assert "compile.min_compile_time_s" in covered
    # program-shaping leaves fan out over every factory, not just one
    fan_fields = {p.field for p in fanout}
    assert {"train.lr", "train.compute_dtype", "fed.epochs",
            "fed.client_parallelism", "server.server_lr"} <= fan_fields


def test_known_benign_classification_has_no_stale_entries():
    """KNOWN_BENIGN must stay a subset of the live RunConfig tree — a
    renamed/removed field would otherwise silently exempt nothing while
    looking like it exempts something."""
    from fedml_tpu.analysis.digest_audit import (
        KNOWN_BENIGN,
        runconfig_leaves,
    )

    leaves = {path for path, _ in runconfig_leaves()}
    assert KNOWN_BENIGN <= leaves, sorted(KNOWN_BENIGN - leaves)


def test_perturbed_value_changes_every_leaf():
    """The derived perturbation value differs from the default for every
    leaf (a no-op perturbation would audit nothing)."""
    from fedml_tpu.analysis.digest_audit import (
        perturbed_value,
        runconfig_leaves,
    )

    for path, value in runconfig_leaves():
        assert perturbed_value(path, value) != value, path


def test_auto_perturbed_choice_fields_stay_buildable():
    """Choice-typed leaves get a legal alternative member (an illegal
    value would turn every audit row into 'rejected' and prove
    nothing): the perturbed fedavg config must still build."""
    from fedml_tpu.analysis.digest_audit import (
        _CHOICE_VALUES,
        base_config,
        config_replace,
    )
    from fedml_tpu.config import (
        CLIENT_OPTIMIZERS,
        PARTITION_METHODS,
        SERVER_OPTIMIZERS,
    )

    choices = {
        "train.client_optimizer": CLIENT_OPTIMIZERS,
        "server.server_optimizer": SERVER_OPTIMIZERS,
        "data.partition_method": PARTITION_METHODS,
        "fed.client_parallelism": ("vmap", "scan", "auto"),
        "train.compute_dtype": ("float32", "bfloat16"),
    }
    cfg = base_config()
    for path, allowed in choices.items():
        assert _CHOICE_VALUES[path] in allowed, path
        config_replace(cfg, path, _CHOICE_VALUES[path])  # must not raise


def test_audit_flags_perturbation_rejected_by_every_factory():
    """A fan-out leaf whose perturbed value is ILLEGAL everywhere (a new
    choice-typed knob missing from _CHOICE_VALUES) must surface as a
    violation — rejected-by-all means unaudited, the exact hole
    auto-derivation exists to close."""
    import dataclasses as dc

    from fedml_tpu.analysis.digest_audit import (
        Perturbation,
        audit_all,
        default_specs,
    )

    spec = [s for s in default_specs() if s.name == "scaffold_round"][0]
    # scaffold's plain-SGD guard rejects momentum; as the ONLY spec in
    # the registry that makes the field rejected-by-every-factory
    lone = dc.replace(spec, perturbations=[Perturbation("train.momentum", 0.9)])
    _, violations = audit_all([lone])
    assert any(
        v.field == "train.momentum" and "EVERY factory" in v.detail
        for v in violations
    ), violations
