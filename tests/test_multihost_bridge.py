"""Cross-process multihost (VERDICT r3 #9 / r4 Weak #7): the DCN story
must cross a REAL OS process boundary.

Two pins:
1. the jax.distributed-on-CPU blocker — the coordination service forms
   the process group but this build's CPU PJRT client never federates
   the device topology. Pinned so that an environment upgrade that fixes
   it fails this test LOUDLY (then parallel/multihost.initialize_multihost
   opens the native path and the pin gets retired);
2. the working alternative — a two-process gRPC-bridged hierarchical
   federation (parallel/hierarchical_bridge.py) whose final global model
   EQUALS the in-process HierarchicalFedAvgAPI simulator at the same
   seed: the bridge runs the simulator's own _group_round per process,
   so this is an equality contract, not a smoke test."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port(span: int = 1) -> int:
    """A port N with N..N+span-1 all currently bindable (GrpcCommManager
    binds base_port + rank, so the bridge needs a free PAIR). Close-then-
    reuse race is acceptable for CI; hardcoded ports collide with
    lingering subprocesses of a previous run, which is worse."""
    import socket

    for _ in range(64):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
        try:
            socks = []
            for off in range(span):
                t = socket.socket()
                t.bind(("127.0.0.1", base + off))
                socks.append(t)
            return base
        except OSError:
            continue
        finally:
            for t in socks:
                t.close()
    raise RuntimeError("no free port span found")


@pytest.mark.slow
def test_jax_distributed_cpu_blocker_is_pinned(tmp_path):
    """Documents (and watches) the backend blocker: np=2 at the
    coordination layer, device_count=1 at the PJRT layer."""
    probe = textwrap.dedent(
        """
        import os, sys, json
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        import jax
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        rank, port = int(sys.argv[1]), sys.argv[2]
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=2, process_id=rank)
        from jax._src import distributed
        print(json.dumps({
            "rank": rank,
            "coord_np": distributed.global_state.num_processes,
            "device_count": jax.device_count(),
            "process_count": jax.process_count(),
        }))
        """
    )
    script = tmp_path / "probe.py"
    script.write_text(probe)
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(rank), port],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        for rank in (0, 1)
    ]
    rows = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            assert p.returncode == 0, out[-500:]
            rows.append(json.loads(
                [l for l in out.splitlines() if l.startswith("{")][-1]
            ))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for row in rows:
        # the coordination layer DOES form the 2-process group…
        assert row["coord_np"] == 2, row
        # …and the device layer does NOT federate — THE pinned blocker.
        # If this assertion ever fails (device_count == 8), the real
        # jax.distributed multihost path has opened on this image:
        # retire this pin and wire initialize_multihost into CI.
        assert row["device_count"] == 1, (
            "jax.distributed CPU device federation now WORKS — retire "
            f"this blocker pin and enable the native path: {row}"
        )


_DRIVER = """
import os, sys, json
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
# match the pytest conftest's PRNG flavor — the oracle equality below
# compares against a simulator running under it
jax.config.update("jax_threefry_partitionable", True)
import numpy as np
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.parallel.hierarchical_bridge import run_hierarchical_grpc_group

rank, port, outdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
cfg = RunConfig(
    data=DataConfig(batch_size=8),
    fed=FedConfig(client_num_in_total=8, client_num_per_round=6,
                  comm_round=3, epochs=1, group_num=2, group_comm_round=2,
                  frequency_of_the_test=10_000),
    train=TrainConfig(client_optimizer="sgd", lr=0.1),
    seed=0,
)
data = synthetic_classification(num_clients=8, num_classes=3, feat_shape=(6,),
                                samples_per_client=16, partition_method="homo",
                                ragged=False, seed=0)
model = create_model("lr", "synthetic", (6,), 3)
api = run_hierarchical_grpc_group(cfg, data, model, rank, base_port=port,
                                  log_fn=lambda r: print(json.dumps(r), flush=True))
import jax
leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(api.global_vars)]
np.savez(os.path.join(outdir, f"final_{rank}.npz"),
         **{str(i): l for i, l in enumerate(leaves)})
print("DONE", rank, flush=True)
"""


@pytest.mark.slow
def test_two_process_grpc_bridged_hierarchical_equals_simulator(tmp_path):
    import jax

    script = tmp_path / "driver.py"
    script.write_text(_DRIVER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # SAME virtual-device config as the in-pytest simulator (conftest):
    # XLA:CPU partitions intra-op work per device count, so a 1-device
    # subprocess would differ from the 8-device simulator at ~1e-4 —
    # the equality contract below needs identical backend config
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    port = str(_free_port(span=2))  # base_port + rank for ranks 0 and 1
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(rank), port, str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        for rank in (1, 0)
    ]
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            assert p.returncode == 0, out[-1500:]
            assert "DONE" in out
    finally:
        for p in procs:  # a hung rank must not outlive the test
            if p.poll() is None:
                p.kill()
                p.wait()
    finals = [
        np.load(tmp_path / f"final_{rank}.npz") for rank in (0, 1)
    ]
    # both processes ended on the SAME global model
    for k in finals[0].files:
        np.testing.assert_array_equal(finals[0][k], finals[1][k])

    # …and that model equals the in-process simulator's (same seed, same
    # _group_round math — equality, not similarity). NOTE: this config
    # block must mirror _DRIVER's verbatim — drift here shows up as a
    # bridge/simulator mismatch, so check both when touching either.
    from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvgAPI
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model

    cfg = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(client_num_in_total=8, client_num_per_round=6,
                      comm_round=3, epochs=1, group_num=2, group_comm_round=2,
                      frequency_of_the_test=10_000),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    data = synthetic_classification(num_clients=8, num_classes=3,
                                    feat_shape=(6,), samples_per_client=16,
                                    partition_method="homo", ragged=False,
                                    seed=0)
    model = create_model("lr", "synthetic", (6,), 3)
    sim = HierarchicalFedAvgAPI(cfg, data, model)
    for r in range(3):
        sim.train_round(r)
    sim_leaves = [
        np.asarray(l) for l in jax.tree_util.tree_leaves(sim.global_vars)
    ]
    # float tolerance, not bitwise: XLA:CPU's intra-op partitioning (and
    # compile-cache provenance) shifts reduction order across process
    # configs at the ~1e-4 level; the cross-RANK equality above stays
    # exact because both ranks run the same binary config
    for i, l in enumerate(sim_leaves):
        np.testing.assert_allclose(
            finals[0][str(i)], l, rtol=2e-3, atol=5e-4
        )
