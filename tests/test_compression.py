"""Uplink update compression (core/compression.py) — codec properties and
the compressed-federation end-to-end path. The reference has no
communication compression anywhere (its wire INFLATES tensors ~4x via JSON
lists, message.py:47-59); this is a beyond-parity transport feature."""

import jax
import numpy as np
import pytest

from fedml_tpu.core import compression as CZ


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": rng.normal(0, 0.02, size=(64, 32)).astype(np.float32),
        "b": rng.normal(0, 0.01, size=(32,)).astype(np.float32),
    }


def test_int8_roundtrip_error_bound():
    t = _tree()
    payload = CZ.encode_int8(t)
    back = CZ.decode_int8(payload, t)
    for k in t:
        scale = float(np.max(np.abs(t[k]))) / 127.0
        assert np.max(np.abs(back[k] - t[k])) <= scale / 2 + 1e-9
    # zero tensors stay exactly zero
    z = {"w": np.zeros((4, 4), np.float32)}
    assert np.all(CZ.decode_int8(CZ.encode_int8(z), z)["w"] == 0)


def test_int8_payload_is_4x_smaller():
    t = _tree()
    raw = CZ.payload_bytes(t)
    comp = CZ.payload_bytes(CZ.encode_int8(t))
    assert comp < raw / 3.5  # int8 payload + fp32 scales


def test_topk_keeps_largest_magnitudes():
    t = {"w": np.arange(-50, 50, dtype=np.float32).reshape(10, 10)}
    back = CZ.decode_topk(CZ.encode_topk(t, frac=0.1), t)["w"].reshape(-1)
    flat = t["w"].reshape(-1)
    kept = np.nonzero(back)[0]
    assert len(kept) == 10
    # the kept entries are exactly the 10 largest |values|
    expect = np.sort(np.argsort(np.abs(flat))[-10:])
    np.testing.assert_array_equal(np.sort(kept), expect)
    np.testing.assert_array_equal(back[kept], flat[kept])


def test_encode_update_symmetry():
    w_round = _tree(1)
    w_local = jax.tree_util.tree_map(
        lambda a: a + np.float32(0.01) * np.sign(a), w_round
    )
    back = CZ.decode_update(
        CZ.encode_update(w_local, w_round, "int8"), w_round, "int8"
    )
    for k in w_round:
        np.testing.assert_allclose(back[k], w_local[k], atol=1e-4)
    with pytest.raises(ValueError):
        CZ.encode_update(w_local, w_round, "gzip")


def test_error_feedback_residual_per_client():
    """The residual memory follows the CLIENT, not the transport rank."""
    t0, t1 = _tree(0), _tree(1)
    ref = jax.tree_util.tree_map(np.zeros_like, t0)
    ef = CZ.TopKErrorFeedback(frac=0.1)
    p0 = ef.encode(0, t0, ref)
    p1 = ef.encode(1, t1, ref)
    r0, r1 = ef._residual[0], ef._residual[1]
    # each residual equals its own delta minus what was sent
    for cid, (t, p, r) in {0: (t0, p0, r0), 1: (t1, p1, r1)}.items():
        sent = CZ.decode_topk(p, t)
        for k in t:
            np.testing.assert_allclose(r[k], t[k] - sent[k], atol=1e-6)
    # round 2 for client 0 ships delta + residual: with a ZERO new delta,
    # the payload is exactly the residual's top-k — the dropped mass from
    # round 1 arrives in round 2
    p0b = ef.encode(0, ref, ref)
    sent_b = CZ.decode_topk(p0b, t0)
    nz = np.nonzero(sent_b["w"].ravel())[0]
    np.testing.assert_allclose(
        sent_b["w"].ravel()[nz], r0["w"].ravel()[nz], atol=1e-6
    )


def test_error_feedback_improves_sparse_topk():
    """At 5% density the one-shot top-k run plateaus above the EF run:
    error feedback ships the dropped coordinates eventually (deterministic
    seeds — this is a reproducible comparison, not a statistical one)."""
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
    from fedml_tpu.config import (
        CommConfig,
        DataConfig,
        FedConfig,
        RunConfig,
        TrainConfig,
    )
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(8,), samples_per_client=24,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(8,),
        num_classes=3, name="lr",
    )
    losses = {}
    for ef in (False, True):
        cfg = RunConfig(
            data=DataConfig(batch_size=-1),
            fed=FedConfig(
                client_num_in_total=4, client_num_per_round=4, comm_round=25,
                epochs=1, frequency_of_the_test=25,
            ),
            train=TrainConfig(client_optimizer="sgd", lr=0.5),
            comm=CommConfig(
                compression="topk", topk_frac=0.05, error_feedback=ef
            ),
            seed=0,
        )
        server = run_loopback_federation(cfg, data, model_def())
        losses[ef] = server.history[-1]["Test/Loss"]
    assert losses[True] < losses[False], losses


def test_error_feedback_partial_participation():
    """Sampling re-assigns clients to ranks each round; the SHARED store
    keyed by client id keeps each residual with its client (a per-rank
    store would orphan them). The run must complete and stay finite."""
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
    from fedml_tpu.config import (
        CommConfig,
        DataConfig,
        FedConfig,
        RunConfig,
        TrainConfig,
    )
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=6, num_classes=3, feat_shape=(8,), samples_per_client=24,
        partition_method="homo", seed=9,
    )
    model_def = ModelDef(
        LogisticRegression(num_classes=3), input_shape=(8,), num_classes=3,
        name="lr",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3, comm_round=8,
            epochs=1, frequency_of_the_test=8,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.5),
        comm=CommConfig(compression="topk", topk_frac=0.1, error_feedback=True),
        seed=0,
    )
    server = run_loopback_federation(cfg, data, model_def)
    assert server.round_idx == 8
    assert np.isfinite(server.history[-1]["Test/Loss"])


@pytest.mark.parametrize("method", ["int8", "topk"])
def test_compressed_loopback_federation(method):
    """Federation over the loopback transport with uplink compression:
    int8 must track the uncompressed simulator closely; topk (50% density
    on this tiny model) must still converge to a working model."""
    from fedml_tpu.algorithms import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
    from fedml_tpu.config import (
        CommConfig,
        DataConfig,
        FedConfig,
        RunConfig,
        TrainConfig,
    )
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(5,), samples_per_client=24,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )
    # full batch (the oracle's deterministic config) so sim vs transport
    # differ ONLY by the codec's reconstruction error. int8 checks param
    # closeness over a few rounds; topk needs enough rounds to show the
    # sparsified run actually learns (4 rounds don't learn even
    # uncompressed — Test/Acc 0.22 at round 3).
    R = 4 if method == "int8" else 40
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=R,
            epochs=1, frequency_of_the_test=R,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.5),
        comm=CommConfig(compression=method, topk_frac=0.5),
        seed=0,
    )
    sim = FedAvgAPI(cfg.replace(comm=CommConfig()), data, model_def())
    sim.train()
    server = run_loopback_federation(cfg, data, model_def())
    assert server.round_idx == R
    sim_leaves = jax.tree_util.tree_leaves(sim.global_vars)
    srv_leaves = jax.tree_util.tree_leaves(server.global_vars)
    if method == "int8":
        # per-round max error = scale/2 of small deltas — stays close
        for a, b in zip(sim_leaves, srv_leaves):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-3
            )
    else:
        # sparsified updates drift more; the model must still beat chance
        acc = server.history[-1]["Test/Acc"]
        assert acc > 0.5, f"topk-compressed run degenerated: acc={acc}"


def test_int4_roundtrip_error_bound_and_packing():
    """Packed 4-bit: q = round(x/s) with s = max|x|/7, two values per
    byte — max error s/2, exact zeros stay exact, odd sizes pack the pad
    nibble without leaking it."""
    t = _tree()
    payload = CZ.encode_int4(t)
    back = CZ.decode_int4(payload, t)
    for k in t:
        scale = float(np.max(np.abs(t[k]))) / 7.0
        assert np.max(np.abs(back[k] - t[k])) <= scale / 2 + 1e-9, k
        assert back[k].shape == t[k].shape
    # odd leaf size: the pad nibble packs but never leaks
    odd = {"v": np.random.default_rng(3).normal(0, 0.1, size=(7,)).astype(
        np.float32
    )}
    p_odd = CZ.encode_int4(odd)
    assert p_odd["q0"].nbytes == (odd["v"].size + 1) // 2
    assert CZ.decode_int4(p_odd, odd)["v"].shape == (7,)
    z = {"w": np.zeros((4, 4), np.float32)}
    assert np.all(CZ.decode_int4(CZ.encode_int4(z), z)["w"] == 0)


def test_int4_payload_is_8x_smaller():
    t = _tree()
    raw = CZ.payload_bytes(t)
    comp = CZ.payload_bytes(CZ.encode_int4(t))
    assert comp < raw / 7.0  # nibble-packed + fp32 scales


def test_topk8_composes_topk_indices_with_int8_values():
    """topk8 keeps EXACTLY topk's index set; values are int8-quantized
    over the kept entries (error <= scale/2)."""
    t = {"w": np.arange(-50, 50, dtype=np.float32).reshape(10, 10)}
    p = CZ.encode_topk(t, frac=0.1)
    p8 = CZ.encode_topk_int8(t, frac=0.1)
    np.testing.assert_array_equal(p["i0"], p8["i0"])
    back = CZ.decode_topk_int8(p8, t)["w"].reshape(-1)
    ref = CZ.decode_topk(p, t)["w"].reshape(-1)
    kept = np.nonzero(ref)[0]
    scale = float(np.max(np.abs(ref[kept]))) / 127.0
    assert np.max(np.abs(back[kept] - ref[kept])) <= scale / 2 + 1e-9
    # the value half of the payload shrank 4x (int8 vs fp32)
    assert p8["v0"].nbytes * 4 == p["v0"].nbytes


def test_error_feedback_generalizes_to_quantizers():
    """ErrorFeedback with method=int4: the residual is exactly the
    quantization error, and it ships next round (dropped mass arrives)."""
    t = _tree(0)
    ref = jax.tree_util.tree_map(np.zeros_like, t)
    ef = CZ.ErrorFeedback(0.1, method="int4")
    p = ef.encode(0, t, ref)
    sent = CZ.decode_delta(p, t, "int4")
    for k in t:
        np.testing.assert_allclose(
            ef._residual[0][k], t[k] - sent[k], atol=1e-6
        )
    # the activation rule follows CommConfig.compression
    class _Comm:
        error_feedback = True
        compression = "int4"
        topk_frac = 0.01

    assert CZ.ErrorFeedback.maybe_from_config(_Comm).method == "int4"
    _Comm.compression = "none"
    assert CZ.ErrorFeedback.maybe_from_config(_Comm) is None
    with pytest.raises(ValueError, match="error feedback"):
        CZ.ErrorFeedback(0.1, method="nope")


def test_int4_reach_target_matches_fp32_uplink():
    """The ISSUE-14 acceptance form: the packed 4-bit uplink WITH error
    feedback reaches the fp32 run's loss target in the same number of
    rounds (the byte cut is free at this operating point — deterministic
    seeds, a reproducible comparison)."""
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
    from fedml_tpu.config import (
        CommConfig,
        DataConfig,
        FedConfig,
        RunConfig,
        TrainConfig,
    )
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(8,), samples_per_client=24,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(8,),
        num_classes=3, name="lr",
    )
    R, target = 20, 0.32

    def reach(comm):
        cfg = RunConfig(
            data=DataConfig(batch_size=-1),
            fed=FedConfig(
                client_num_in_total=4, client_num_per_round=4, comm_round=R,
                epochs=1, frequency_of_the_test=1,
            ),
            train=TrainConfig(client_optimizer="sgd", lr=0.5),
            comm=comm,
            seed=0,
        )
        server = run_loopback_federation(cfg, data, model_def())
        for row in server.history:
            if row.get("Test/Loss") is not None and row["Test/Loss"] <= target:
                return row["round"]
        return None

    r_fp32 = reach(CommConfig())
    r_int4 = reach(CommConfig(compression="int4", error_feedback=True))
    assert r_fp32 is not None, "fp32 arm never reached target"
    assert r_int4 == r_fp32, (r_int4, r_fp32)


def test_sim_transport_cohort_and_numerics_parity_under_int4():
    """Partial participation under the 4-bit codec: the transport server
    must select byte-identical cohorts to the vmap simulator (codec
    cannot perturb scheduling), and the model must track the simulator
    within the quantizer's error envelope."""
    from fedml_tpu.algorithms import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
    from fedml_tpu.config import (
        CommConfig,
        DataConfig,
        FedConfig,
        RunConfig,
        TrainConfig,
    )
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=6, num_classes=3, feat_shape=(5,), samples_per_client=24,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )
    R = 6
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3, comm_round=R,
            epochs=1, frequency_of_the_test=R,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.5),
        comm=CommConfig(compression="int4", error_feedback=True),
        seed=0,
    )
    sim = FedAvgAPI(cfg.replace(comm=CommConfig()), data, model_def())
    sim.train()
    server = run_loopback_federation(cfg, data, model_def())
    assert server.round_idx == R
    # cohort parity: the scheduler draw is identical per round
    for r in range(R):
        np.testing.assert_array_equal(
            sim._round_plan(r)[0], server.scheduler.select(r, k=3)
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(server.global_vars),
    ):
        # 4-bit grid: per-round error scale/2 = max|delta|/14 — an order
        # coarser than int8's, but error feedback keeps the trajectory
        # tracking (measured drift ~7e-3 at round 6)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-2)
