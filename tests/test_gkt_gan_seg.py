"""FedGKT (representation exchange + KD), FedGAN (adversarial FedAvg), and
FedSeg (per-pixel task + mIoU evaluator) smoke/oracle tests on tiny shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.base import FederatedDataset


def test_kl_loss_zero_when_equal():
    from fedml_tpu.algorithms.fedgkt import kl_loss

    logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 5)), jnp.float32)
    assert float(kl_loss(logits, logits, temperature=3.0)) < 1e-5
    other = logits + 1.5 * jnp.asarray(np.random.default_rng(1).normal(size=(4, 5)))
    assert float(kl_loss(logits, other, temperature=3.0)) > 0.01


def test_fedgkt_round_and_eval():
    from fedml_tpu.algorithms.fedgkt import FedGKTAPI

    rng = np.random.default_rng(2)
    H = 8
    means = rng.normal(0, 2, size=(3, H * H * 3))
    clients = []
    for _ in range(2):
        y = rng.integers(0, 3, 32)
        x = (means[y] + rng.normal(0, 0.5, (32, H * H * 3))).astype(np.float32)
        clients.append((x.reshape(-1, H, H, 3), y))

    api = FedGKTAPI(num_classes=3, input_shape=(H, H, 3), client_blocks=1, server_layers=(1, 1), lr=0.05)
    cache = api.train_round(clients, batch_size=16)
    assert set(cache.keys()) == {0, 1}
    assert cache[0].shape == (32, 3)  # per-sample server logits back
    # second round consumes the cache (KD path)
    cache = api.train_round(clients, batch_size=16, server_logits_cache=cache)
    acc = api.evaluate(clients[0][0], clients[0][1], client_id=0)
    assert 0.0 <= acc <= 1.0


def test_fedgan_round():
    from fedml_tpu.algorithms.fedgan import FedGANAPI

    rng = np.random.default_rng(3)
    clients_x = [rng.normal(0, 1, (24, 28, 28, 1)).astype(np.float32) for _ in range(3)]
    data = FederatedDataset(
        name="mnist_gan",
        client_x=clients_x,
        client_y=[np.zeros(24, np.int32) for _ in range(3)],
        test_x=np.zeros((8, 28, 28, 1), np.float32),
        test_y=np.zeros(8, np.int32),
        num_classes=1,
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(client_num_in_total=3, client_num_per_round=2, comm_round=2, epochs=1),
        train=TrainConfig(lr=2e-4),
    )
    api = FedGANAPI(cfg, data)
    final = api.train()
    assert np.isfinite(final["Train/G_Loss"]) and np.isfinite(final["Train/D_Loss"])
    fake = api.generate(4)
    assert fake.shape == (4, 28, 28, 1)
    assert float(jnp.max(jnp.abs(fake))) <= 1.0 + 1e-5  # tanh range


def _seg_data(num_clients=3, n=12, H=16, C=4):
    rng = np.random.default_rng(5)
    xs, ys = [], []
    for _ in range(num_clients):
        x = rng.normal(size=(n, H, H, 3)).astype(np.float32)
        y = rng.integers(0, C, size=(n, H, H)).astype(np.int32)
        # left half encodes class 0 strongly; inject signal
        x[..., : H // 2, 0] += 3.0 * (y[:, :, : H // 2] == 0)
        y[:, 0, 0] = 255  # some ignore pixels
        xs.append(x)
        ys.append(y)
    return FederatedDataset(
        name="seg_synth",
        client_x=xs,
        client_y=ys,
        test_x=xs[0].copy(),
        test_y=ys[0].copy(),
        num_classes=C,
    )


def test_fedseg_round_and_miou():
    from fedml_tpu.algorithms.fedseg import FedSegAPI
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.segnet import EncoderDecoder

    data = _seg_data()
    model = ModelDef(
        EncoderDecoder(num_classes=4, width=8),
        (16, 16, 3),
        4,
        has_batch_stats=True,
        name="encdec",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=4),
        fed=FedConfig(client_num_in_total=3, client_num_per_round=3, comm_round=2, epochs=1, frequency_of_the_test=2),
        train=TrainConfig(lr=0.05),
    )
    api = FedSegAPI(cfg, data, model)
    final = api.train()
    assert 0.0 <= final["Test/mIoU"] <= 1.0
    assert 0.0 <= final["Test/FWIoU"] <= 1.0
    assert np.isfinite(final["Train/Loss"])


def test_evaluator_perfect_prediction():
    from fedml_tpu.utils.seg_metrics import Evaluator

    ev = Evaluator(3)
    gt = np.array([[0, 1, 2, 255]])
    ev.add_batch(gt, np.array([[0, 1, 2, 0]]))
    assert ev.Pixel_Accuracy() == 1.0  # ignore-index pixel excluded
    assert ev.Mean_Intersection_over_Union() == 1.0


def test_evaluator_partial():
    from fedml_tpu.utils.seg_metrics import Evaluator

    ev = Evaluator(2)
    ev.add_batch(np.array([0, 0, 1, 1]), np.array([0, 1, 1, 1]))
    # class0: inter 1, union 2 -> 0.5 ; class1: inter 2, union 3 -> 2/3
    np.testing.assert_allclose(
        ev.Mean_Intersection_over_Union(), (0.5 + 2 / 3) / 2, rtol=1e-6
    )
