"""Shared-memory local transport (TRPC-equivalent backend, ref
fedml_core/distributed/communication/trpc/trpc_comm_manager.py:25-114):
one-copy send / zero-copy receive semantics, echo over the Observer contract,
federation==simulator oracle, and a latency sweep mirroring the reference's
inline TRPC benchmark (trpc_comm_manager.py:146-211)."""

import tempfile
import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.comm import Observer
from fedml_tpu.core.message import Message
from fedml_tpu.core.shm_comm import ShmCommManager


def test_wire_parts_and_write_into():
    m = Message("t", 1, 2)
    arr = np.arange(20, dtype=np.float32).reshape(4, 5)
    m.add_params("w", arr)
    m.add_params("n", 7)
    size = m.wire_size()
    buf = bytearray(size)
    assert m.write_into(buf) == size
    out = Message.from_bytes(bytes(buf))
    np.testing.assert_array_equal(out.get("w"), arr)
    assert out.get("n") == 7


def test_from_bytes_zero_copy_aliases_buffer():
    m = Message("t", 0, 1)
    m.add_params("w", np.zeros(8, dtype=np.float32))
    buf = bytearray(m.wire_size())
    m.write_into(buf)
    out = Message.from_bytes(buf, copy=False)
    w = out.get("w")
    assert not w.flags.owndata  # aliases, does not own
    # mutating the underlying buffer is visible through the array
    one = np.float32(1.0).tobytes()
    tail = len(buf) - 4
    buf[tail : tail + 4] = one
    assert w[-1] == 1.0
    # copy=True must NOT alias
    out2 = Message.from_bytes(buf, copy=True)
    w2 = out2.get("w")
    buf[tail : tail + 4] = np.float32(2.0).tobytes()
    assert w2[-1] == 1.0


class _Collect(Observer):
    def __init__(self):
        self.got = []
        self.event = threading.Event()

    def receive_message(self, msg_type, msg):
        # copy out: zero-copy arrays are valid only inside the callback
        self.got.append((msg_type, {k: np.array(v) if isinstance(v, np.ndarray) else v
                                    for k, v in msg.params.items()}))
        self.event.set()


@pytest.mark.parametrize("zero_copy", [False, True])
def test_shm_echo(zero_copy):
    with tempfile.TemporaryDirectory() as d:
        a = ShmCommManager(0, d, zero_copy=zero_copy)
        b = ShmCommManager(1, d, zero_copy=zero_copy)
        obs = _Collect()
        b.add_observer(obs)
        t = threading.Thread(target=b.handle_receive_message, daemon=True)
        t.start()
        msg = Message("ping", 0, 1)
        payload = np.random.default_rng(0).normal(size=(64, 64)).astype(np.float32)
        msg.add_params("w", payload)
        msg.add_params("round", 5)
        a.send_message(msg)
        assert obs.event.wait(10)
        kind, params = obs.got[0]
        assert kind == "ping"
        np.testing.assert_array_equal(params["w"], payload)
        assert params["round"] == 5
        b.stop_receive_message()
        a.stop_receive_message()
        t.join(timeout=10)
        assert not t.is_alive()


def test_shm_handler_exception_not_masked():
    """A raising observer must propagate its own exception (not BufferError
    from closing a still-referenced segment) and must not leak the segment."""

    class _Boom(Observer):
        def receive_message(self, msg_type, msg):
            raise KeyError("no handler for " + msg_type)

    with tempfile.TemporaryDirectory() as d:
        a = ShmCommManager(0, d)
        b = ShmCommManager(1, d, zero_copy=True)
        b.add_observer(_Boom())
        errs = []

        def loop():
            try:
                b.handle_receive_message()
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        a.send_message(Message("mystery", 0, 1).add_params("w", np.ones(4)))
        t.join(timeout=10)
        assert not t.is_alive()
        assert len(errs) == 1 and isinstance(errs[0], KeyError)
        a.stop_receive_message()
        b.stop_receive_message()


def test_shm_federation_matches_simulator():
    import jax

    from fedml_tpu.algorithms import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_transport import run_shm_federation
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression

    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(5,), samples_per_client=12,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=3,
            epochs=1, frequency_of_the_test=3,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    sim = FedAvgAPI(cfg, data, model_def())
    sim.train()

    server = run_shm_federation(cfg, data, model_def())
    assert server.round_idx == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(server.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_shm_latency_sweep():
    """Parity with the reference's inline TRPC benchmark
    (trpc_comm_manager.py:146-211): round-trip a sweep of tensor sizes;
    assert sanity (finite, monotone-ish in payload), not absolute numbers."""
    with tempfile.TemporaryDirectory() as d:
        a = ShmCommManager(0, d)
        b = ShmCommManager(1, d, zero_copy=True)
        obs = _Collect()
        b.add_observer(obs)
        t = threading.Thread(target=b.handle_receive_message, daemon=True)
        t.start()
        stats = {}
        for n in (1_000, 1_000_000):
            payload = np.ones(n, dtype=np.float32)
            t0 = time.perf_counter()
            reps = 5
            for _ in range(reps):
                obs.event.clear()
                msg = Message("bench", 0, 1).add_params("w", payload)
                a.send_message(msg)
                assert obs.event.wait(10)
            stats[n] = (time.perf_counter() - t0) / reps
        b.stop_receive_message()
        a.stop_receive_message()
        t.join(timeout=10)
        assert all(v > 0 and np.isfinite(v) for v in stats.values())
        # gross sanity only — absolute latency is CI-load-dependent
        assert stats[1_000_000] < 2.0
