"""Straggler/deadline tolerance in the transport runtime (FedConfig
.deadline_s/.min_clients). The reference's aggregator barrier waits forever
for every sampled client (FedAVGAggregator.py:43-49; SURVEY §5 "no straggler
mitigation") — here the server aggregates the partial set once the deadline
passes with a quorum, and discards the straggler's late round-tagged upload."""

import time

import numpy as np

from fedml_tpu.algorithms.fedavg_transport import (
    LocalTrainer,
    run_federation,
    run_loopback_federation,
)
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression


def _data():
    return synthetic_classification(
        num_clients=3, num_classes=3, feat_shape=(5,), samples_per_client=12,
        partition_method="homo", seed=9,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )


def _cfg(**fed_kw):
    base = dict(
        client_num_in_total=3, client_num_per_round=3, comm_round=2,
        epochs=1, frequency_of_the_test=1,
    )
    base.update(fed_kw)
    return RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(**base),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


class _SlowTrainer(LocalTrainer):
    def __init__(self, *a, delay_s=0.0, **kw):
        super().__init__(*a, **kw)
        self.delay_s = delay_s

    def train(self, round_idx, variables):
        time.sleep(self.delay_s)
        return super().train(round_idx, variables)


def test_deadline_completes_round_without_straggler():
    data, model = _data(), _model()
    # 4 rounds at a 1 s deadline keep the server alive ~4.5 s, so the
    # straggler's 2.5 s-late round-0 upload lands while it is still serving
    # (round ~2) and must be discarded by the round tag
    cfg = _cfg(deadline_s=1.0, min_clients=2, comm_round=4)
    hub = LoopbackHub()

    def trainer_factory(rank):
        # rank 3 is a straggler: slower than the deadline every round
        return _SlowTrainer(
            cfg, data, model, "classification",
            delay_s=2.5 if rank == 3 else 0.0,
        )

    t0 = time.perf_counter()
    server = run_federation(
        cfg,
        data,
        model,
        lambda rank: LoopbackCommManager(hub, rank),
        trainer_factory=trainer_factory,
    )
    wall = time.perf_counter() - t0
    # all rounds completed without waiting for the straggler each round
    assert server.round_idx == 4
    assert len(server.history) == 4
    assert all(np.isfinite(r["Test/Loss"]) for r in server.history)
    # the straggler's late round-0 upload was discarded, not mixed in —
    # i.e. the round closed at the deadline, not at the straggler's pace
    assert server.dropped_uploads >= 1
    # gross bound only (run_federation joins the straggler thread, which
    # still finishes its ~6 s trainings before exiting on FINISH)
    assert wall < 30.0


def test_no_deadline_keeps_reference_semantics():
    """deadline_s=0 (default): server waits for every client — parity with
    the all-received barrier, same result as the plain loopback run."""
    import jax

    data, model = _data(), _model()
    ref = run_loopback_federation(_cfg(), data, model)
    hub = LoopbackHub()
    got = run_federation(
        _cfg(), data, model, lambda rank: LoopbackCommManager(hub, rank)
    )
    assert got.dropped_uploads == 0
    for a, b in zip(
        jax.tree_util.tree_leaves(ref.global_vars),
        jax.tree_util.tree_leaves(got.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
