"""Rank-selection Pallas kernel for the robust aggregators
(ops/robust_stats.py): the unrolled stable-rank compare-accumulate must
select exactly the multiset a stable sort's trim window keeps — pinned
against the jnp sort reference across cohort sizes, trim windows, ties,
and the median's odd/even middle semantics. Kernel runs in interpret mode
here (CPU); on TPU the same code compiles via Mosaic."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from fedml_tpu.ops.robust_stats import (  # noqa: E402
    median_1d,
    median_trim_k,
    trimmed_mean_1d,
)


def _ref_trimmed(x, k):
    s = np.sort(x, axis=0)
    return np.mean(s[k : x.shape[0] - k], axis=0)


@pytest.mark.parametrize("C", [3, 4, 5, 8, 10, 16])
def test_kernel_matches_sort_reference(C):
    x = np.random.default_rng(C).normal(size=(C, 700)).astype(np.float32)
    for k in range((C - 1) // 2 + 1):
        if 2 * k >= C:
            continue
        got = np.asarray(
            trimmed_mean_1d(jnp.asarray(x), k, use_kernel=True, interpret=True)
        )
        np.testing.assert_allclose(
            got, _ref_trimmed(x, k), atol=1e-6, rtol=1e-6
        )


@pytest.mark.parametrize("C", [3, 4, 7, 8])
def test_median_matches_numpy_even_and_odd(C):
    x = np.random.default_rng(C + 50).normal(size=(C, 300)).astype(np.float32)
    got = np.asarray(median_1d(jnp.asarray(x), use_kernel=True, interpret=True))
    np.testing.assert_allclose(got, np.median(x, axis=0), atol=1e-6, rtol=1e-6)


def test_ties_select_the_stable_sort_multiset_exactly():
    """Integer-valued floats: the kept multiset sums exactly, so the
    kernel must be bit-equal to the sort reference even under heavy
    ties (the stable index tie-break is load-bearing here)."""
    x = (
        np.random.default_rng(0)
        .integers(-3, 4, size=(6, 500))
        .astype(np.float32)
    )
    got = np.asarray(
        trimmed_mean_1d(jnp.asarray(x), 1, use_kernel=True, interpret=True)
    )
    np.testing.assert_array_equal(got, _ref_trimmed(x, 1).astype(np.float32))


def test_block_padding_boundary():
    """D not a multiple of the 512 block (and tiny D): the zero-padded
    lanes must never leak into real outputs."""
    for D in (1, 5, 127, 513, 700):
        x = np.random.default_rng(D).normal(size=(5, D)).astype(np.float32)
        got = np.asarray(
            trimmed_mean_1d(jnp.asarray(x), 1, use_kernel=True, interpret=True)
        )
        assert got.shape == (D,)
        np.testing.assert_allclose(
            got, _ref_trimmed(x, 1), atol=1e-6, rtol=1e-6
        )


def test_fallback_path_is_sort_based():
    """use_kernel=False takes the historical XLA lowering — literally the
    sort-and-mean formula (byte-identity off-TPU is the production
    contract; robustness/robust_aggregation.py gates on backend)."""
    x = np.random.default_rng(1).normal(size=(6, 64)).astype(np.float32)
    got = np.asarray(trimmed_mean_1d(jnp.asarray(x), 1, use_kernel=False))
    ref = np.asarray(jnp.mean(jnp.sort(jnp.asarray(x), axis=0)[1:5], axis=0))
    np.testing.assert_array_equal(got, ref)


def test_median_trim_k_semantics():
    assert median_trim_k(3) == 1  # keep 1 (odd)
    assert median_trim_k(5) == 2
    assert median_trim_k(4) == 1  # keep 2 (even): mean of middle two
    assert median_trim_k(6) == 2


def test_bad_trim_window_rejected():
    x = jnp.zeros((4, 8), jnp.float32)
    with pytest.raises(ValueError, match="trim_k"):
        trimmed_mean_1d(x, 2, use_kernel=True, interpret=True)
    with pytest.raises(ValueError, match="trim_k"):
        trimmed_mean_1d(x, -1, use_kernel=False)
