"""Oracle-style reduction tests for the algorithm variants:

- FedOpt with server SGD lr=1.0 IS FedAvg (w_old − 1.0·(w_old − w_avg) = w_avg)
  — the identity the reference's pseudo-gradient construction relies on
  (FedOptAggregator.py:109-117).
- FedNova with equal client sample counts and equal local steps reduces to
  FedAvg (a_i identical ⇒ τ_eff = a ⇒ w' = Σ p_i w_i).
- Hierarchical FedAvg with group_comm_round=1 equals flat FedAvg under
  full-batch E=1 for ANY group split — the reference's CI oracle
  (CI-script-fedavg.sh:52-58).
"""

import jax
import numpy as np
import pytest

from fedml_tpu.config import (
    DataConfig,
    FedConfig,
    RunConfig,
    ServerConfig,
    TrainConfig,
)
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.algorithms import (
    FedAvgAPI,
    FedNovaAPI,
    FedOptAPI,
    HierarchicalFedAvgAPI,
)

NUM_CLIENTS = 8
NUM_CLASSES = 5
FEAT = (6,)


def _data(ragged=True):
    return synthetic_classification(
        num_clients=NUM_CLIENTS,
        num_classes=NUM_CLASSES,
        feat_shape=FEAT,
        samples_per_client=24,
        partition_method="homo",
        ragged=ragged,
        seed=5,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=NUM_CLASSES),
        input_shape=FEAT,
        num_classes=NUM_CLASSES,
        name="lr",
    )


def _cfg(**over):
    base = dict(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=NUM_CLIENTS,
            comm_round=4,
            epochs=1,
            frequency_of_the_test=4,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=3,
    )
    base.update(over)
    return RunConfig(**base)


def _assert_trees_close(a, b, atol=1e-5):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol, rtol=1e-5)


def test_fedopt_sgd_lr1_equals_fedavg():
    data = _data()
    cfg = _cfg(server=ServerConfig(server_optimizer="sgd", server_lr=1.0))
    avg = FedAvgAPI(cfg, data, _model())
    avg.train()
    opt = FedOptAPI(cfg, data, _model())
    opt.train()
    _assert_trees_close(avg.global_vars, opt.global_vars)


def test_fedopt_adam_learns():
    data = _data()
    cfg = _cfg(
        server=ServerConfig(server_optimizer="adam", server_lr=0.05),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=4,
            comm_round=15,
            epochs=1,
            frequency_of_the_test=15,
        ),
    )
    api = FedOptAPI(cfg, data, _model())
    final = api.train()
    assert final["Test/Acc"] > 0.5


def test_fednova_equal_clients_equals_fedavg():
    # Equal shard sizes + full batch => tau_i identical => FedNova == FedAvg.
    data = _data(ragged=False)
    cfg = _cfg(data=DataConfig(batch_size=-1))
    avg = FedAvgAPI(cfg, data, _model())
    avg.train()
    nova = FedNovaAPI(cfg, data, _model())
    nova.train()
    _assert_trees_close(avg.global_vars, nova.global_vars)


def test_fednova_rejects_unsupported():
    data = _data()
    with pytest.raises(ValueError):
        FedNovaAPI(_cfg(train=TrainConfig(client_optimizer="adam")), data, _model())
    with pytest.raises(ValueError):
        FedNovaAPI(_cfg(train=TrainConfig(prox_mu=0.1)), data, _model())


def test_fednova_ragged_learns():
    data = _data(ragged=True)
    cfg = _cfg(
        train=TrainConfig(client_optimizer="sgd", lr=0.1, momentum=0.9),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=NUM_CLIENTS,
            comm_round=15,
            epochs=2,
            frequency_of_the_test=15,
        ),
    )
    api = FedNovaAPI(cfg, data, _model())
    final = api.train()
    assert final["Test/Acc"] > 0.5


def test_hierarchical_oracle_equals_flat():
    """Full batch, E=1, group_comm_round=1, full participation: hierarchical
    == flat FedAvg for any group split (ref CI-script-fedavg.sh:52-58)."""
    data = _data()
    cfg = _cfg(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=NUM_CLIENTS,
            comm_round=3,
            epochs=1,
            frequency_of_the_test=3,
            group_num=3,
            group_comm_round=1,
        ),
    )
    flat = FedAvgAPI(cfg, data, _model())
    flat.train()
    hier = HierarchicalFedAvgAPI(cfg, data, _model())
    hier.train()
    _assert_trees_close(flat.global_vars, hier.global_vars)


def test_hierarchical_multi_subround_learns():
    data = _data()
    cfg = _cfg(
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=NUM_CLIENTS,
            comm_round=8,
            epochs=1,
            frequency_of_the_test=8,
            group_num=2,
            group_comm_round=2,
        ),
    )
    api = HierarchicalFedAvgAPI(cfg, data, _model())
    final = api.train()
    assert final["Test/Acc"] > 0.5
