"""Compile runtime (fedml_tpu/compile/): program dedup, digest stability,
AOT warmup numerics parity, and the hardened persistent cache's
corruption-proofing (ISSUE 4 acceptance contract).

The quarantine/recompile tests drive REAL jax compiles through the
hardened store in subprocesses, so a (hypothetical) deserialization fault
can never poison this pytest process — exactly the isolation discipline
the store exists to enforce."""

import json
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from fedml_tpu.compile import (
    CachedProgram,
    HardenedFileCache,
    ProgramCache,
    call_signature,
    canonical,
    compile_snapshot,
    compile_summary_row,
    get_program_cache,
    model_fingerprint,
    program_digest,
)
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression

# ---------------------------------------------------------------------------
# shared fixtures (mirror tests/test_scheduler.py so the ProgramCache
# actually dedupes across the two modules — that sharing IS the feature)
# ---------------------------------------------------------------------------


def _data(num_clients=6, samples=12):
    return synthetic_classification(
        num_clients=num_clients, num_classes=3, feat_shape=(5,),
        samples_per_client=samples, partition_method="homo", seed=9,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )


def _cfg(**fed_kw):
    base = dict(
        client_num_in_total=6, client_num_per_round=3, comm_round=2,
        epochs=1, frequency_of_the_test=1,
    )
    base.update(fed_kw)
    return RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(**base),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


# ---------------------------------------------------------------------------
# digest: canonicalization + cross-process stability
# ---------------------------------------------------------------------------


def test_canonical_abstracts_arrays_to_shape_dtype():
    """Concrete values NEVER enter a digest — two arrays of the same
    shape/dtype canonicalize identically, different shapes differ."""
    a = canonical(np.zeros((2, 3), np.float32))
    b = canonical(np.ones((2, 3), np.float32) * 7)
    c = canonical(np.zeros((2, 4), np.float32))
    assert a == b
    assert a != c
    assert a == {"__aval__": [[2, 3], "float32"]}


def test_canonical_dict_order_independent():
    f1 = {"x": {"b": 2, "a": 1}, "y": [1, 2]}
    f2 = {"y": [1, 2], "x": {"a": 1, "b": 2}}
    assert program_digest(f1) == program_digest(f2)


def test_digest_distinguishes_configs():
    t1 = TrainConfig(lr=0.1)
    t2 = TrainConfig(lr=0.2)
    assert program_digest({"train": t1}) != program_digest({"train": t2})
    assert program_digest({"train": t1}) == program_digest(
        {"train": TrainConfig(lr=0.1)}
    )


def test_digest_stable_across_processes():
    """The plain-field digest (configs, shapes, strings) is the persistent
    keying contract — pin it against a fresh interpreter."""
    fields_src = (
        "{'kind': 'round', 'train': TrainConfig(lr=0.05, momentum=0.9), "
        "'epochs': 2, 'task': 'classification', "
        "'x': np.zeros((4, 8), np.float32)}"
    )
    prog = (
        "import numpy as np\n"
        "from fedml_tpu.config import TrainConfig\n"
        "from fedml_tpu.compile.digest import program_digest\n"
        f"print(program_digest({fields_src}))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        check=True, timeout=120,
    )
    from fedml_tpu.config import TrainConfig as TC

    here = program_digest({
        "kind": "round", "train": TC(lr=0.05, momentum=0.9),
        "epochs": 2, "task": "classification",
        "x": np.zeros((4, 8), np.float32),
    })
    assert out.stdout.strip() == here


# ---------------------------------------------------------------------------
# ProgramCache: hit/miss accounting + factory dedup
# ---------------------------------------------------------------------------


def test_program_cache_hit_miss_accounting():
    pc = ProgramCache()
    built = []

    def builder():
        built.append(1)
        return lambda x: x

    p1 = pc.get_or_build("p", {"k": 1}, builder)
    p2 = pc.get_or_build("p", {"k": 1}, builder)
    p3 = pc.get_or_build("p", {"k": 2}, builder)
    assert p1 is p2 and p1 is not p3
    assert len(built) == 2  # one build per distinct digest
    assert pc.stats()["hits"] == 1
    assert pc.stats()["misses"] == 2
    u = pc.wrap_uncached("opaque", lambda x: x)
    assert isinstance(u, CachedProgram)
    assert pc.stats()["bypassed"] == 1


def test_round_factories_dedupe_onto_one_program(program_cache):
    """Two independently constructed FedAvg round factories over the same
    (model, config) land on ONE CachedProgram — the compile-once-per-shape
    contract. An opaque hook must bypass the registry."""
    from fedml_tpu.algorithms.fedavg import make_fedavg_round

    model, cfg = _model(), _cfg()
    before = program_cache.stats()
    f1 = make_fedavg_round(model, cfg)
    f2 = make_fedavg_round(model, cfg)
    # the dispatch wrappers differ but resolve to the same cached program
    # (vmap mode collapses both may_pad variants onto one skip choice)
    assert f1.variant_for(False) is f2.variant_for(False)
    assert f1.variant_for(True) is f2.variant_for(True)
    after = program_cache.stats()
    assert after["hits"] >= before["hits"] + 1
    f3 = make_fedavg_round(
        model, cfg, post_aggregate=lambda g: g  # opaque hook
    )
    assert f3.variant_for(False) is not f1.variant_for(False)
    assert program_cache.stats()["bypassed"] > before["bypassed"]


def test_eval_factory_dedupes(program_cache):
    from fedml_tpu.train.evaluate import make_eval_fn

    model = _model()
    assert make_eval_fn(model) is make_eval_fn(model)


def test_fedopt_server_step_dedupes_across_vmap_and_transport(program_cache):
    """The vmap API (fedopt.py) and the transport server manager
    (fedavg_transport.py) key the FedOpt server step on the SAME
    (kind, server config, step_builder) fields, so both sides share ONE
    jit object. The probe below issues the transport-side call verbatim
    with a must-not-run builder — if either site's key drifts, the miss
    invokes the builder and the test fails."""
    from fedml_tpu.algorithms.fedopt import FedOptAPI, make_server_step
    from fedml_tpu.config import ServerConfig

    cfg = _cfg()
    api = FedOptAPI(cfg, _data(), _model(), log_fn=lambda *a, **k: None)
    probe = program_cache.get_or_build(
        "server_opt",
        {
            "kind": "fedopt_server_step",
            "server": cfg.server,
            "step_builder": make_server_step,
        },
        lambda: pytest.fail("transport-side key missed the vmap-side program"),
    )
    assert probe is api._server_step
    # a different server config is a different program
    assert probe.digest != program_digest(
        {
            "kind": "fedopt_server_step",
            "server": ServerConfig(server_lr=0.5),
            "step_builder": make_server_step,
        }
    )


def test_model_fingerprint_distinguishes_architectures():
    m1 = _model()
    m2 = ModelDef(
        module=LogisticRegression(num_classes=4), input_shape=(5,),
        num_classes=4, name="lr",
    )
    assert model_fingerprint(m1) != model_fingerprint(m2)
    assert model_fingerprint(m1) == model_fingerprint(_model())


def test_compile_summary_row_is_baseline_relative():
    pc = get_program_cache()
    base = compile_snapshot()
    pc.get_or_build("t", {"unique": "test_compile_summary_row"}, lambda: (lambda x: x))
    row = compile_summary_row(base)
    assert row["compile/cache_misses"] == 1
    assert row["compile/cache_hits"] == 0


# ---------------------------------------------------------------------------
# CachedProgram: AOT warmup surface
# ---------------------------------------------------------------------------


def test_warmup_compiles_and_dispatches_aot():
    import jax
    import jax.numpy as jnp

    pc = ProgramCache()
    prog = pc.wrap_uncached("f", jax.jit(lambda x: jnp.sin(x) + 1))
    x = np.ones((8,), np.float32)
    st = prog.warmup(x)
    assert st["aot_cache_hit"] is False
    assert st["compile_s"] > 0
    assert pc.stats()["compile_s"] == pytest.approx(st["compile_s"])
    # idempotent per signature: the second warmup is a hit
    st2 = prog.warmup(x)
    assert st2["aot_cache_hit"] is True
    # the warmed executable serves the call and matches the jit path
    np.testing.assert_array_equal(
        np.asarray(prog(x)), np.asarray(jax.jit(lambda x: jnp.sin(x) + 1)(x))
    )
    # a different shape class falls back to the ordinary jit path
    y = np.ones((4,), np.float32)
    np.testing.assert_allclose(np.asarray(prog(y)), np.sin(y) + 1, rtol=1e-6)


def test_call_signature_separates_shape_classes():
    a = (np.zeros((2, 3), np.float32),)
    b = (np.zeros((2, 3), np.float32) + 5,)
    c = (np.zeros((3, 2), np.float32),)
    assert call_signature(a) == call_signature(b)
    assert call_signature(a) != call_signature(c)


# ---------------------------------------------------------------------------
# warmup-vs-cold numerics parity (byte-identical round results)
# ---------------------------------------------------------------------------


def _tree_equal(t1, t2):
    import jax

    l1, d1 = jax.tree_util.tree_flatten(t1)
    l2, d2 = jax.tree_util.tree_flatten(t2)
    assert d1 == d2
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_warmup_vs_cold_numerics_parity_vmap():
    """--warmup only lowers/compiles — it executes nothing, consumes no
    RNG, and touches no training state, so warmed runs produce
    byte-identical models (the acceptance-criteria parity clause)."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    data, model = _data(), _model()
    cold = FedAvgAPI(_cfg(), data, model)
    cold.train()
    warm = FedAvgAPI(_cfg(), data, model)
    rows = warm.warmup(log_fn=lambda r: None)
    assert "compile/warmup_s" in rows
    warm.train()
    _tree_equal(cold.global_vars, warm.global_vars)


def test_warmup_fused_chunk_memo_and_parity():
    """When the planner would fuse (start_round mid-chunk — round 0 itself
    is always an eval round, so fresh runs warm the eager variant), warmup
    AOT-compiles the fused chunk program AND memoizes the whole plan so
    train_rounds_fused doesn't rebuild/re-ship the chunk's index/mask
    arrays; numerics stay byte-identical to a cold run."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    data, model = _data(), _model()
    cfg = RunConfig(
        data=DataConfig(batch_size=4),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3, comm_round=5,
            epochs=1, frequency_of_the_test=4, fused_rounds=4,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    cold = FedAvgAPI(cfg, data, model)
    cold.start_round = 1
    assert cold._fused_chunk_len(1) == 4  # the branch under test is live
    cold.train()
    warm = FedAvgAPI(cfg, data, model)
    warm.start_round = 1
    rows = warm.warmup(log_fn=lambda r: None)
    # the chunk program was warmed: either really compiled, or adopted
    # from the session executable store (a REPEAT pytest session
    # deserializes what the previous one exported — compile_s is then 0
    # by contract and the _deserialized row says so)
    assert rows.get("compile/round_fused_compile_s", 0) > 0 or rows.get(
        "compile/round_fused_deserialized"
    ), rows
    assert (1, 4) in warm._warm_fused  # plan memo populated by warmup...
    warm.train()
    assert not warm._warm_fused  # ...and consumed at dispatch
    _tree_equal(cold.global_vars, warm.global_vars)


def test_warmup_vs_cold_numerics_parity_loopback():
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation

    data, model = _data(), _model()
    cold = run_loopback_federation(_cfg(), data, model)
    warm = run_loopback_federation(_cfg(), data, model, warmup=True)
    _tree_equal(cold.global_vars, warm.global_vars)


# ---------------------------------------------------------------------------
# HardenedFileCache: integrity, quarantine, atomicity
# ---------------------------------------------------------------------------


def test_hardened_cache_roundtrip(tmp_path):
    c = HardenedFileCache(str(tmp_path))
    assert c.get("k1") is None
    c.put("k1", b"payload-bytes")
    assert c.get("k1") == b"payload-bytes"
    assert c.stats() == {
        "hits": 1, "misses": 1, "puts": 1, "quarantined": 0, "evicted": 0,
    }


def test_hardened_cache_size_cap_evicts_lru(tmp_path, monkeypatch):
    """jax_compilation_cache_max_size parity: the hardened store enforces
    the size cap the stock LRUCache honored, evicting least-recently-used
    entries (never the one just written)."""
    c = HardenedFileCache(str(tmp_path))
    monkeypatch.setattr(
        HardenedFileCache, "_max_size_bytes", staticmethod(lambda: 150)
    )
    c.put("old", b"x" * 60)
    time.sleep(0.05)  # distinct timestamps order the LRU scan
    c.put("mid", b"y" * 60)
    time.sleep(0.05)
    c.put("new", b"z" * 60)  # framed total now exceeds the 150-byte cap
    assert c.get("new") == b"z" * 60
    assert c.get("old") is None  # oldest evicted
    assert c.stats()["evicted"] >= 1
    assert c.stats()["quarantined"] == 0


def test_hardened_cache_first_writer_wins(tmp_path):
    c = HardenedFileCache(str(tmp_path))
    c.put("k", b"first")
    c.put("k", b"second")
    assert c.get("k") == b"first"
    assert c.stats()["puts"] == 1


def test_hardened_cache_quarantines_truncated_entry(tmp_path):
    """A torn/truncated entry returns a MISS (the program recompiles) and
    is moved into quarantine/ — never wrong bytes."""
    c = HardenedFileCache(str(tmp_path))
    c.put("k", b"x" * 256)
    (entry,) = tmp_path.glob("*.ftpc")
    blob = entry.read_bytes()
    entry.write_bytes(blob[: len(blob) // 2])
    assert c.get("k") is None
    assert c.stats()["quarantined"] == 1
    assert not entry.exists()
    assert len(list((tmp_path / "quarantine").iterdir())) == 1
    # the slot is writable again — recompile then hit
    c.put("k", b"y" * 256)
    assert c.get("k") == b"y" * 256


def test_hardened_cache_rejects_bit_rot(tmp_path):
    c = HardenedFileCache(str(tmp_path))
    c.put("k", b"A" * 64)
    (entry,) = tmp_path.glob("*.ftpc")
    blob = bytearray(entry.read_bytes())
    blob[-1] ^= 0xFF  # flip one payload bit
    entry.write_bytes(bytes(blob))
    assert c.get("k") is None
    assert c.stats()["quarantined"] == 1


def test_hardened_cache_ignores_stock_format_files(tmp_path):
    """A directory previously populated by the stock jax cache is treated
    as empty (our entries carry the .ftpc suffix + magic), not misread."""
    (tmp_path / "jit_foo-deadbeef").write_bytes(b"stock cache bytes")
    c = HardenedFileCache(str(tmp_path))
    assert c.get("jit_foo-deadbeef") is None
    assert c.stats()["quarantined"] == 0


# ---------------------------------------------------------------------------
# end-to-end: real jax compiles through the hardened store (subprocesses)
# ---------------------------------------------------------------------------

_E2E_PROG = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp
from fedml_tpu.compile import install_hardened_cache
c = install_hardened_cache(sys.argv[1], min_compile_time_secs=0.0)
assert c is not None, "hardened cache failed to install on this jax"
f = jax.jit(lambda x: jnp.sin(x) @ x.T)
x = np.arange(64 * 64, dtype=np.float32).reshape(64, 64) / 4096.0
r = np.asarray(f(x))
print(json.dumps({"stats": c.stats(), "sum": float(r.sum())}))
"""


def _run_e2e(cache_dir):
    out = subprocess.run(
        [sys.executable, "-c", _E2E_PROG, str(cache_dir)],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_e2e_persistent_cache_hit_and_corruption_recovery(tmp_path):
    """Three fresh processes over one cache dir: (1) cold compile + put;
    (2) integrity-verified hit; (3) after on-disk truncation, the loader
    quarantines and RECOMPILES to the same numerics instead of
    deserializing garbage — the PR 3 incident class, closed."""
    r1 = _run_e2e(tmp_path)
    assert r1["stats"]["puts"] >= 1
    r2 = _run_e2e(tmp_path)
    assert r2["stats"]["hits"] >= 1
    assert r2["sum"] == r1["sum"]
    for p in pathlib.Path(tmp_path).glob("*.ftpc"):
        blob = p.read_bytes()
        p.write_bytes(blob[: len(blob) // 2])
    r3 = _run_e2e(tmp_path)
    assert r3["stats"]["quarantined"] >= 1
    assert r3["stats"]["hits"] == 0
    assert r3["sum"] == r1["sum"]
    assert (pathlib.Path(tmp_path) / "quarantine").exists()


# ---------------------------------------------------------------------------
# session fixture contract
# ---------------------------------------------------------------------------


def test_program_cache_fixture_is_the_global_registry(program_cache):
    assert program_cache is get_program_cache()


def test_install_run_cache_restores_previous_binding(tmp_path):
    """A run-scoped cache install must not hijack later compiles in a
    long-lived process: restore() reinstates the prior binding (here: the
    conftest-installed shared hardened store)."""
    import jax

    from fedml_tpu.compile import install_run_cache, installed_cache

    prev = installed_cache()
    prev_dir = jax.config.jax_compilation_cache_dir
    cache, restore = install_run_cache(str(tmp_path), min_compile_time_secs=3.0)
    assert installed_cache() is cache
    assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    restore()
    assert installed_cache() is prev
    assert jax.config.jax_compilation_cache_dir == prev_dir


# ---------------------------------------------------------------------------
# serialized executable cache: zero-cold-start persistence (ISSUE 8)
# ---------------------------------------------------------------------------


def _exec_jit():
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda v: jnp.sin(v) @ v.T)


def _exec_prog(digest_key, pc=None):
    pc = pc or ProgramCache()
    return pc.get_or_build("p", {"k": digest_key}, _exec_jit), pc


def test_executable_cache_warmup_roundtrip(tmp_path):
    """Cold warmup compiles + persists; a FRESH program object with the
    same canonical digest warms by DESERIALIZING (compile_s == 0), and
    dispatches byte-identically — the executable on disk IS the one a
    compile would have built."""
    from fedml_tpu.compile import install_run_executable_cache

    x = np.arange(36, dtype=np.float32).reshape(6, 6) / 11
    cache, restore = install_run_executable_cache(str(tmp_path))
    try:
        if cache is None:
            pytest.skip("this jaxlib cannot serialize AOT executables")
        prog1, _ = _exec_prog("xc-roundtrip")
        st1 = prog1.warmup(x)
        assert st1["compile_s"] > 0 and not st1.get("deserialized")
        assert cache.stats()["puts"] == 1
        r1 = np.asarray(prog1(x))

        prog2, pc2 = _exec_prog("xc-roundtrip")
        st2 = prog2.warmup(x)
        assert st2["deserialized"] is True
        assert st2["compile_s"] == 0.0
        assert st2["deserialize_s"] > 0
        assert pc2.stats()["deserialize_hits"] == 1
        np.testing.assert_array_equal(r1, np.asarray(prog2(x)))
        # summary keys: the ProgramCache row carries the headline counters
        row = pc2.summary_row()
        assert row["compile/deserialize_hits"] == 1
        assert row["compile/deserialize_s"] > 0
    finally:
        restore()


def test_executable_cache_lazy_dispatch_adopts_from_disk(tmp_path):
    """A shape class nobody warmed in THIS process still dispatches with
    zero compiles when a predecessor persisted it: the first call per
    signature probes the store before paying a compile."""
    from fedml_tpu.compile import install_run_executable_cache

    x = np.arange(16, dtype=np.float32).reshape(4, 4) / 7
    cache, restore = install_run_executable_cache(str(tmp_path))
    try:
        if cache is None:
            pytest.skip("this jaxlib cannot serialize AOT executables")
        prog1, _ = _exec_prog("xc-lazy")
        prog1.warmup(x)
        r1 = np.asarray(prog1(x))
        prog2, pc2 = _exec_prog("xc-lazy")
        r2 = np.asarray(prog2(x))  # no warmup — plain dispatch
        np.testing.assert_array_equal(r1, r2)
        assert pc2.stats()["deserialize_hits"] == 1
        assert prog2._aot  # adopted into the AOT dispatch map
    finally:
        restore()


@pytest.mark.parametrize("corruption", ["truncate", "bit_rot", "env_skew"])
def test_executable_cache_poisoned_entry_quarantined_and_recompiles(
    tmp_path, corruption
):
    """The three poisoning classes of the new on-disk format — torn
    write/truncation, bit rot, and a wrong environment fingerprint
    (version skew / a cache dir copied across machines) — must all
    quarantine the entry and RECOMPILE to identical numerics, never
    deserialize a wrong executable (the acceptance-criteria mirror of
    PR 4's corrupt-entry contract)."""
    import pickle

    from fedml_tpu.compile import install_run_executable_cache

    x = np.arange(25, dtype=np.float32).reshape(5, 5) / 9
    cache, restore = install_run_executable_cache(str(tmp_path))
    try:
        if cache is None:
            pytest.skip("this jaxlib cannot serialize AOT executables")
        prog1, _ = _exec_prog("xc-poison")
        prog1.warmup(x)
        r1 = np.asarray(prog1(x))
        (entry,) = tmp_path.glob("xc-*.ftpc")
        blob = entry.read_bytes()
        if corruption == "truncate":
            entry.write_bytes(blob[: len(blob) // 2])
        elif corruption == "bit_rot":
            rot = bytearray(blob)
            rot[-1] ^= 0xFF
            entry.write_bytes(bytes(rot))
        else:  # env_skew: valid frame + pickle, mismatched fingerprint
            payload = HardenedFileCache._verify(blob)
            doc = pickle.loads(payload)
            doc["env"] = dict(doc["env"], jaxlib="0.0.0-skew")
            entry.write_bytes(
                HardenedFileCache._frame(
                    pickle.dumps(doc, protocol=pickle.HIGHEST_PROTOCOL)
                )
            )

        prog2, _ = _exec_prog("xc-poison")
        st2 = prog2.warmup(x)
        # the poisoned entry must NOT have been adopted: a real compile
        assert not st2.get("deserialized")
        assert st2["compile_s"] > 0
        np.testing.assert_array_equal(r1, np.asarray(prog2(x)))
        stats = cache.stats()
        assert stats["quarantined"] + stats["store"]["quarantined"] >= 1
        assert (tmp_path / "quarantine").exists()
    finally:
        restore()


def test_environment_fingerprint_pins_version_and_code():
    """The fingerprint carries everything that must match for a persisted
    executable to be safe here — jax/jaxlib versions, backend, topology,
    lowering-relevant flags, and a hash of the package source (a code
    edit must invalidate every entry)."""
    from fedml_tpu.compile import environment_fingerprint

    env = environment_fingerprint()
    for key in ("jax", "jaxlib", "backend", "device_kind", "device_count",
                "threefry_partitionable", "xla_flags", "code"):
        assert key in env, key
    assert len(env["code"]) == 64  # sha256 over the package source
    assert env == environment_fingerprint()  # stable within a process


def test_executable_cache_key_separates_environments(tmp_path):
    """Environment skew lands on a DIFFERENT key — a cache dir shared by
    two jaxlib versions never even reads the other's entries."""
    from fedml_tpu.compile.executable_cache import ExecutableCache

    c1 = ExecutableCache(str(tmp_path))
    c2 = ExecutableCache(str(tmp_path))
    sig = (("treedef"), ((4, 4), "float32"))
    k1 = c1.key_for("d" * 64, sig)
    c2._env_doc = dict(c1._env() or {}, jaxlib="0.0.0-skew")
    assert c2.key_for("d" * 64, sig) != k1
    assert c1.key_for("d" * 64, sig) == k1  # deterministic


def test_wrap_uncached_programs_never_persist(tmp_path):
    """Opaque (bypassed) programs have no canonical digest — they must
    not enter the executable store (an over-merged key would be silent
    wrong numerics, exactly the class the digest discipline exists
    for)."""
    from fedml_tpu.compile import install_run_executable_cache

    x = np.ones((4,), np.float32)
    cache, restore = install_run_executable_cache(str(tmp_path))
    try:
        if cache is None:
            pytest.skip("this jaxlib cannot serialize AOT executables")
        prog = ProgramCache().wrap_uncached("opaque", _exec_jit())
        prog.warmup(np.ones((2, 2), np.float32))
        _ = prog(np.ones((2, 2), np.float32))
        assert cache.stats()["puts"] == 0
        assert not list(tmp_path.glob("xc-*.ftpc"))
    finally:
        restore()


# ---------------------------------------------------------------------------
# shape-class pre-enumeration: no lazy compiles after round 0 (ISSUE 8)
# ---------------------------------------------------------------------------


def _multiclass_data(sizes=(8, 33, 90)):
    """A partition spanning len(sizes) distinct bucket_steps classes at
    batch_size=8 (steps 1 / 8 / 16 — pinned below)."""
    rng = np.random.default_rng(0)
    from fedml_tpu.data.base import FederatedDataset

    return FederatedDataset(
        name="multiclass",
        client_x=[rng.normal(size=(n, 5)).astype(np.float32) for n in sizes],
        client_y=[rng.integers(0, 3, size=(n,)).astype(np.int32) for n in sizes],
        test_x=rng.normal(size=(20, 5)).astype(np.float32),
        test_y=rng.integers(0, 3, size=(20,)).astype(np.int32),
        num_classes=3,
    )


def _multiclass_cfg():
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=3, client_num_per_round=1, comm_round=8,
            epochs=1, frequency_of_the_test=1,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def test_partition_shape_classes_enumerates_singleton_buckets():
    from fedml_tpu.data.base import partition_shape_classes

    classes = partition_shape_classes([8, 33, 90], 8, 1)
    assert set(classes) == {(1, 8), (8, 8), (16, 8)}
    assert classes[(1, 8)] == 0 and classes[(16, 8)] == 2


@pytest.fixture
def warmed_multiclass_api(program_cache):
    """A warmed API over a >=3-shape-class partition, plus a completed
    cold run of the identical config — so every utility program (metric
    packing, RNG folds, the flush concat) is already compiled and the
    recompile budget below measures EXACTLY the lazy shape-bucket
    compiles warmup is supposed to have eliminated."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    data, cfg = _multiclass_data(), _multiclass_cfg()
    model = _model()
    cold = FedAvgAPI(cfg, data, model)
    cold.train()
    # sanity: the round-seeded draws really visit all three classes
    visited = {cold._round_plan(r)[0][0] for r in range(cfg.fed.comm_round)}
    assert visited == {0, 1, 2}, visited
    warm = FedAvgAPI(cfg, data, model)
    rows = warm.warmup(log_fn=lambda r: None)
    # the warmup set was derived from the PARTITION, not round 0's cohort
    for klass in ("s1b8", "s8b8", "s16b8"):
        assert f"compile/round_{klass}_compile_s" in rows, sorted(rows)
    return cold, warm


@pytest.mark.recompile_budget(0)
def test_no_lazy_shape_bucket_compiles_after_warmup(
    warmed_multiclass_api, recompile_sentinel
):
    """ISSUE 8 acceptance: a multi-round run whose client sizes span >= 3
    bucket_steps classes runs with a post-warmup recompile budget of ZERO
    — rounds 1..R never hit a lazy shape-bucket compile (the fixture runs
    before the sentinel starts, so the budget window is exactly
    post-warmup) — and stays byte-identical to the cold run."""
    cold, warm = warmed_multiclass_api
    warm.train()
    _tree_equal(cold.global_vars, warm.global_vars)


def test_warmup_local_train_covers_whole_partition():
    """The transport warmup barrier enumerates every shape class in the
    partition (client_ids=None default), not just round 0's cohort — a
    later round's differently-bucketed client must not race a lazy
    compile against the deadline."""
    from fedml_tpu.compile import warmup_local_train
    from fedml_tpu.algorithms.fedavg_transport import shared_local_train

    data, cfg = _multiclass_data(), _multiclass_cfg()
    model = _model()
    gv = model.init(__import__("jax").random.PRNGKey(0))
    rows = warmup_local_train(
        shared_local_train(model, cfg, "classification"), cfg, data, gv
    )
    labels = {k for k in rows if k.startswith("compile/local_train_s")}
    assert {
        "compile/local_train_s1b8_compile_s",
        "compile/local_train_s8b8_compile_s",
        "compile/local_train_s16b8_compile_s",
    } <= labels, sorted(labels)


# ---------------------------------------------------------------------------
# end-to-end: zero-cold-start across REAL process boundaries (subprocesses)
# ---------------------------------------------------------------------------

_XC_E2E_PROG = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp
from fedml_tpu.compile import ProgramCache, install_executable_cache
from fedml_tpu.analysis.sentinel import RecompileSentinel
cache = install_executable_cache(sys.argv[1])
if cache is None:
    print(json.dumps({"unsupported": True})); raise SystemExit(0)
s = RecompileSentinel().start()
pc = ProgramCache()
prog = pc.get_or_build(
    "p", {"k": "xc-e2e"}, lambda: jax.jit(lambda v: jnp.sin(v) @ v.T)
)
x = np.arange(64 * 64, dtype=np.float32).reshape(64, 64) / 4096.0
st = prog.warmup(x)
r = np.asarray(prog(x))
s.stop()
print(json.dumps({
    "stats": cache.stats(), "deserialized": bool(st.get("deserialized")),
    "recompiles": s.recompiles(), "sum": float(r.sum()),
}))
"""


def _run_xc_e2e(cache_dir):
    out = subprocess.run(
        [sys.executable, "-c", _XC_E2E_PROG, str(cache_dir)],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_e2e_executable_cache_zero_cold_start_and_poison_recovery(tmp_path):
    """Three fresh processes over one executable-cache dir: (1) cold
    warmup compiles + persists; (2) a FRESH PROCESS deserializes instead
    of compiling — zero backend compiles, identical numerics (the
    zero-cold-start contract); (3) after on-disk corruption the loader
    quarantines and recompiles to the same numerics — never a wrong
    executable."""
    r1 = _run_xc_e2e(tmp_path)
    if r1.get("unsupported"):
        pytest.skip("this jaxlib cannot serialize AOT executables")
    assert r1["stats"]["puts"] >= 1 and not r1["deserialized"]
    assert r1["recompiles"] >= 1
    r2 = _run_xc_e2e(tmp_path)
    assert r2["deserialized"] is True
    assert r2["stats"]["hits"] >= 1
    assert r2["recompiles"] == 0, r2
    assert r2["sum"] == r1["sum"]
    for p in pathlib.Path(tmp_path).glob("xc-*.ftpc"):
        blob = p.read_bytes()
        p.write_bytes(blob[: len(blob) // 2])
    r3 = _run_xc_e2e(tmp_path)
    assert not r3["deserialized"]
    assert r3["stats"]["quarantined"] + r3["stats"]["store"]["quarantined"] >= 1
    assert r3["sum"] == r1["sum"]


def test_class_enumeration_skips_unreachable_classes():
    """A class whose bucket has fewer clients at-or-below it than the
    cohort size can never be a cohort max (sampling without replacement)
    — warmup must not waste compiles and cache entries on it; a
    shrinkable (cohort=1) enumeration keeps it."""
    from fedml_tpu.compile.warmup import _classes_by_population

    counts = [8, 100, 100, 100]
    full, _ = _classes_by_population(counts, 8, 1, cohort=4)
    assert (1, 8) not in dict(full)           # unreachable at cohort 4
    assert len(full) == 1                      # only the 100-sample class
    single, _ = _classes_by_population(counts, 8, 1, cohort=1)
    assert (1, 8) in dict(single)              # reachable as a singleton
