"""Robust FedAvg on the mesh runtime == the vmap runtime, defense by
defense (clip, weak-DP, and the all_gather-backed Byzantine aggregators)."""

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg_robust import RobustFedAvgAPI
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.parallel import RobustDistributedFedAvgAPI
from fedml_tpu.robustness import RobustConfig


def _setup():
    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(5,), samples_per_client=16,
        partition_method="homo", ragged=False, seed=4,
    )
    model = ModelDef(
        LogisticRegression(num_classes=3), input_shape=(5,), num_classes=3,
        name="lr",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=8, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=8, client_num_per_round=8, comm_round=2,
            epochs=1, frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    return cfg, data, model


@pytest.mark.parametrize(
    "defense",
    ["norm_diff_clipping", "weak_dp", "median", "trimmed_mean", "multi_krum"],
)
def test_mesh_robust_matches_vmap(defense):
    cfg, data, model = _setup()
    robust = RobustConfig(
        defense_type=defense, norm_bound=0.5, stddev=0.01, num_byzantine=1,
        multi_krum_m=3,
    )
    sim = RobustFedAvgAPI(cfg, data, model, robust=robust)
    mesh_api = RobustDistributedFedAvgAPI(cfg, data, model, robust=robust)
    for r in range(cfg.fed.comm_round):
        sim.train_round(r)
        mesh_api.train_round(r)
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(mesh_api.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )


def test_mesh_byzantine_rejects_padding():
    cfg, data, model = _setup()
    cfg = cfg.replace(
        fed=FedConfig(
            client_num_in_total=8, client_num_per_round=6, comm_round=1,
            epochs=1,
        )
    )
    with pytest.raises(ValueError, match="divisible by the mesh"):
        RobustDistributedFedAvgAPI(
            cfg, data, model, robust=RobustConfig(defense_type="median")
        )
