"""Round flight recorder (telemetry/flight.py): bounded ring semantics,
byte budget, span-stream folding, and sim-vs-transport record parity."""

import json

from fedml_tpu.telemetry.flight import _RECORD_BYTES, FlightRecorder
from fedml_tpu.telemetry.metrics import MetricsRegistry
from fedml_tpu.telemetry.spans import Tracer


def _drive_round(tracer, r, clients=4, with_eval=False):
    with tracer.span("select", round=r, policy="uniform", clients=clients):
        pass
    with tracer.span("round", round=r):
        with tracer.span("broadcast", round=r, clients=clients):
            pass
        with tracer.span("local_train", round=r, clients=clients):
            pass
        with tracer.span("aggregate", round=r, n_uploads=clients):
            pass
        if with_eval:
            with tracer.span("eval", round=r):
                pass


# ---------------------------------------------------------------------------
# bounded-ring / byte-budget contract (the acceptance-criteria pin)
# ---------------------------------------------------------------------------


def test_ring_stays_flat_over_500_rounds():
    """The K-round ring must never grow with round count: 500 folded
    rounds leave exactly `capacity` records, an empty pending table, and
    a flat serialized footprint between the 100-round and 500-round
    marks."""
    tracer = Tracer()
    reg = MetricsRegistry()
    rec = FlightRecorder(max_rounds=32, registry=reg)
    rec.attach(tracer)
    size_at_100 = None
    for r in range(500):
        _drive_round(tracer, r)
        if r == 99:
            size_at_100 = len(json.dumps(rec.tail()))
    assert rec.rounds_folded == 500
    tail = rec.tail()
    assert len(tail) == rec.capacity == 32
    # ring holds exactly the LAST K rounds
    assert [t["round"] for t in tail] == list(range(468, 500))
    # flat memory: the serialized ring at 500 rounds is the size it was
    # at 100 to within digit-count noise (record shape is fixed — the
    # same phases every round; only numerals like "round": 468 vs 68
    # differ)
    assert abs(len(json.dumps(tail)) - size_at_100) < 0.02 * size_at_100
    assert rec.approx_bytes() == 32 * _RECORD_BYTES
    # nothing left half-open
    assert not rec._pending
    # gauges exported
    assert reg.get("fedml_flight_rounds_folded").value() == 500
    assert reg.get("fedml_flight_round_seconds").value(q="p50") > 0


def test_byte_budget_tightens_capacity_below_max_rounds():
    rec = FlightRecorder(max_rounds=10_000, budget_bytes=8 * _RECORD_BYTES)
    assert rec.capacity == 8
    # and the round-count bound wins when IT is tighter
    rec2 = FlightRecorder(max_rounds=4, budget_bytes=1 << 20)
    assert rec2.capacity == 4


def test_pending_table_is_bounded_for_abandoned_rounds():
    """Phase spans whose round never folds (fedbuff dispatch tags, a
    crashed attempt mid-round) must not accumulate open state."""
    tracer = Tracer()
    rec = FlightRecorder(max_rounds=8)
    rec.attach(tracer)
    for r in range(200):  # broadcast only — the round never completes
        with tracer.span("broadcast", round=r):
            pass
    assert len(rec._pending) <= 16
    assert rec.rounds_folded == 0


# ---------------------------------------------------------------------------
# folding semantics
# ---------------------------------------------------------------------------


def test_fold_captures_phases_cohort_and_straggler_spread():
    tracer = Tracer()
    rec = FlightRecorder(max_rounds=8)
    rec.attach(tracer)
    with tracer.span("round", round=0):
        with tracer.span("broadcast", round=0, clients=3):
            pass
        # three client threads' local_train spans fold into spread stats
        for _ in range(3):
            with tracer.span("local_train", round=0):
                pass
        with tracer.span("aggregate", round=0, n_uploads=3):
            pass
    r = rec.last()
    assert r["round"] == 0
    assert set(r["phases"]) == {"broadcast", "local_train", "aggregate"}
    assert r["clients"] == 3
    assert r["train_n"] == 3
    assert r["train_p50_s"] is not None
    assert r["train_max_s"] >= r["train_p50_s"]
    assert r["t_s"] > 0


def test_late_eval_merges_into_folded_record():
    """The vmap sim logs eval from its deferred metrics path — after the
    round span already folded. The phase must merge into the ring record
    instead of opening a phantom pending round."""
    tracer = Tracer()
    rec = FlightRecorder(max_rounds=8)
    rec.attach(tracer)
    _drive_round(tracer, 0)
    with tracer.span("eval", round=0):
        pass
    assert "eval" in rec.last()["phases"]
    assert not rec._pending


def test_server_step_spans_fold_as_async_records():
    """FedBuff has no round lifecycle: each server_step span IS one
    record (keyed by version)."""
    tracer = Tracer()
    rec = FlightRecorder(max_rounds=8)
    rec.attach(tracer)
    for v in range(5):
        with tracer.span("server_step", version=v, n_deltas=2,
                         staleness_max=0):
            pass
    assert rec.rounds_folded == 5
    assert rec.last()["phases"].get("server_step") is not None


def test_comm_and_recompile_deltas_are_per_round():
    class FakeMeter:
        def __init__(self):
            self.bytes = 0

        def snapshot(self):
            return {
                "bytes_sent": {"m": self.bytes},
                "bytes_received": {"m": self.bytes},
                "messages_sent": {"m": self.bytes // 100},
                "send_retries": {},
            }

    meter = FakeMeter()
    compiles = {"n": 0}
    tracer = Tracer()
    rec = FlightRecorder(
        max_rounds=8, comm_meter=meter, recompiles_fn=lambda: compiles["n"]
    )
    rec.attach(tracer)
    meter.bytes = 1000
    _drive_round(tracer, 0)
    assert rec.last()["comm_bytes_sent"] == 1000
    meter.bytes = 1500
    compiles["n"] = 2
    _drive_round(tracer, 1)
    assert rec.last()["comm_bytes_sent"] == 500  # the DELTA, not the total
    assert rec.last()["recompiles"] == 2
    _drive_round(tracer, 2)
    assert rec.last()["recompiles"] == 0


def test_fold_listener_fires_and_errors_are_contained():
    tracer = Tracer()
    rec = FlightRecorder(max_rounds=8)
    rec.attach(tracer)
    seen = []

    def boom(record):
        seen.append(record["round"])
        raise RuntimeError("listener bug")

    rec.add_listener(boom)
    _drive_round(tracer, 0)
    _drive_round(tracer, 1)
    assert seen == [0, 1]
    assert rec.rounds_folded == 2  # the listener's crash stayed contained


def test_attach_is_idempotent_and_switchable():
    t1, t2 = Tracer(), Tracer()
    rec = FlightRecorder(max_rounds=4)
    rec.attach(t1)
    rec.attach(t1)  # no double-subscription
    _drive_round(t1, 0)
    assert rec.rounds_folded == 1
    rec.attach(t2)  # switching detaches from t1
    _drive_round(t1, 1)
    assert rec.rounds_folded == 1
    _drive_round(t2, 2)
    assert rec.rounds_folded == 2


def test_begin_attempt_fences_restarted_rounds():
    """The supervised-restart contract: a crashed attempt's partial
    round record stays as crash history, and the re-run of that round
    folds a FRESH record — its phases never merge into the dead one."""
    tracer = Tracer()
    rec = FlightRecorder(max_rounds=8)
    rec.attach(tracer)
    # attempt 1: round 0 completes, round 1 crashes mid-round (the round
    # span still records on exception — only broadcast ran)
    _drive_round(tracer, 0)
    with tracer.span("broadcast", round=1):
        pass
    with tracer.span("round", round=1):
        pass  # truncated: no local_train/aggregate
    crashed = rec.tail()[-1]
    assert crashed["round"] == 1
    assert set(crashed["phases"]) == {"broadcast"}
    # attempt 2 (supervisor rebuild): fence, then re-run round 1 fully
    rec.begin_attempt()
    _drive_round(tracer, 1)
    tail = rec.tail()
    # the crashed partial is untouched history; the re-run is a new record
    assert [t["round"] for t in tail] == [0, 1, 1]
    assert set(tail[1]["phases"]) == {"broadcast"}  # still the crash shape
    assert {"broadcast", "local_train", "aggregate"} <= set(
        tail[2]["phases"]
    )
    # late merges target the NEW record for that round, not the sealed one
    with tracer.span("eval", round=1):
        pass
    tail = rec.tail()
    assert "eval" in tail[2]["phases"] and "eval" not in tail[1]["phases"]
    assert not rec._pending


def test_rounds_per_s_excludes_the_restart_gap():
    """A supervised restart's crash + backoff gap must not depress the
    rolling rate (it would fire spurious slo_min_rounds_per_s breaches
    for up to K rounds after every recovery)."""
    clock = {"t": 0.0}
    tracer = Tracer()
    rec = FlightRecorder(max_rounds=16, clock=lambda: clock["t"])
    rec.attach(tracer)
    for r in range(3):  # attempt 1: one round per second
        clock["t"] += 1.0
        _fold_round(tracer, r)
    rec.begin_attempt()
    clock["t"] += 120.0  # the crash + backoff gap
    for r in range(3, 6):  # attempt 2: still one round per second
        clock["t"] += 1.0
        _fold_round(tracer, r)
    # only the current attempt's records count: 2 intervals / 2 s = 1 r/s
    assert rec.rounds_per_s() == 1.0
    assert rec.summary_row()["flight/rounds_per_s"] == 1.0


def _fold_round(tracer, r):
    with tracer.span("round", round=r):
        pass


def test_plain_unscoped_session_skips_recording_entirely():
    """No scope, no ambient recorder, no SLOs -> no flight recorder: the
    wrapper entry points must not pay per-round fold work (or pollute
    the global registry's gauges) for data nobody reads."""
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model
    from fedml_tpu.serve import FedSession

    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(10,),
        samples_per_client=16, partition_method="homo", seed=0,
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=2, comm_round=1,
            epochs=1, frequency_of_the_test=100,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1), seed=0,
    )
    s = FedSession(
        cfg, data, create_model("lr", "synthetic", (10,), 3), name="plain"
    )
    s.run()
    assert s.flight is None
    assert "flight/rounds_folded" not in s.summary_row()


def test_session_adopts_ambient_recorder_instead_of_double_folding():
    """A CLI run with telemetry attaches ONE recorder to the global
    tracer; the wrapper FedSession must adopt it, not stack a second one
    that double-folds every round and fights over the same gauges."""
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model
    from fedml_tpu.serve import FedSession
    from fedml_tpu.telemetry import get_global_tracer

    tracer = get_global_tracer()
    cli_rec = FlightRecorder(max_rounds=16)
    cli_rec.attach(tracer)
    try:
        data = synthetic_classification(
            num_clients=4, num_classes=3, feat_shape=(10,),
            samples_per_client=16, partition_method="homo", seed=0,
        )
        cfg = RunConfig(
            data=DataConfig(batch_size=8),
            fed=FedConfig(
                client_num_in_total=4, client_num_per_round=2,
                comm_round=2, epochs=1, frequency_of_the_test=100,
            ),
            train=TrainConfig(client_optimizer="sgd", lr=0.1), seed=0,
        )
        s = FedSession(
            cfg, data, create_model("lr", "synthetic", (10,), 3),
            name="adopt",
        )
        s.run()
        assert s.flight is cli_rec  # adopted, not duplicated
        assert cli_rec.rounds_folded == 2  # each round folded ONCE
    finally:
        cli_rec.detach()


def test_from_config_reads_population_bounds():
    from fedml_tpu.config import PopulationConfig, RunConfig

    cfg = RunConfig(
        population=PopulationConfig(flight_rounds=5, flight_budget_bytes=1 << 20)
    )
    assert FlightRecorder.from_config(cfg).capacity == 5


# ---------------------------------------------------------------------------
# sim-vs-transport parity on the shared record fields
# ---------------------------------------------------------------------------

_SHARED_FIELDS = {
    "round", "t_s", "ts", "phases", "clients", "train_n", "train_p50_s",
    "train_max_s", "stragglers", "clients_seen",
}


def test_sim_and_transport_records_share_the_core_schema():
    """A vmap-sim run and a loopback transport run must produce flight
    records with the same core fields (values differ — the schema is the
    parity contract the introspection endpoints consume)."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model
    from fedml_tpu.serve import FedSession
    from fedml_tpu.telemetry import TelemetryScope

    data = synthetic_classification(
        num_clients=6, num_classes=3, feat_shape=(10,),
        samples_per_client=24, partition_method="homo", seed=0,
    )
    model = create_model("lr", "synthetic", (10,), 3)
    cfg = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3, comm_round=2,
            epochs=1, frequency_of_the_test=100,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    # transport: the session owns its recorder (scope-resident)
    scope = TelemetryScope(tenant="parity")
    FedSession(cfg, data, model, name="parity", scope=scope).run()
    transport_rec = scope.flight.last()
    # sim: attach a recorder to the global tracer the API records into
    from fedml_tpu.telemetry import get_global_tracer

    sim_flight = FlightRecorder(max_rounds=8)
    sim_flight.attach(get_global_tracer())
    try:
        FedAvgAPI(cfg, data, model).train()
    finally:
        sim_flight.detach()
    sim_rec = sim_flight.last()
    assert sim_rec is not None and transport_rec is not None
    assert _SHARED_FIELDS <= set(sim_rec)
    assert _SHARED_FIELDS <= set(transport_rec)
    for rec in (sim_rec, transport_rec):
        assert rec["t_s"] > 0
        assert rec["clients"] == 3
        assert "broadcast" in rec["phases"] and "local_train" in rec["phases"]
