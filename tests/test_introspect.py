"""Live introspection API (serve/introspect.py + the exporter route
table): /status, /tenants/<name>, /compile, /healthz contracts, unknown
paths 404, scrape-under-churn validity, and the status CLI printer."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.serve import FederationServer
from fedml_tpu.telemetry import (
    MetricsRegistry,
    PrometheusExporter,
    TenantedRegistryView,
)


def _data():
    return synthetic_classification(
        num_clients=6, num_classes=3, feat_shape=(10,),
        samples_per_client=24, partition_method="homo", seed=0,
    )


def _model():
    return create_model("lr", "synthetic", (10,), 3)


def _cfg(comm_round=3, **fed_kw):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3,
            comm_round=comm_round, epochs=1, frequency_of_the_test=100,
            **fed_kw,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as resp:
        return resp.status, json.loads(resp.read().decode())


def _spin(pred, what, timeout=60.0):
    t0 = time.monotonic()
    while not pred():
        assert time.monotonic() - t0 < timeout, f"timed out: {what}"
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# endpoint contracts against a live service
# ---------------------------------------------------------------------------


def test_status_tenants_compile_healthz_contracts(tmp_path):
    data, model = _data(), _model()
    srv = FederationServer(prom_port=0)
    cp = str(tmp_path / "ck")
    srv.create_session(
        "intro_a", _cfg(comm_round=40), data, model, algorithm="fedavg",
        checkpoint_path=cp, checkpoint_every=1,
    )
    srv.create_session("intro_b", _cfg(comm_round=3), data, model)
    srv.start()
    port = srv.prom_port
    try:
        a = srv.session("intro_a")
        # mid-flight: rounds monotonically advancing in /status
        _spin(lambda: a.server is not None and a.server.round_idx >= 2,
              "intro_a progress")
        st1 = _get(port, "/status")[1]
        assert st1["tenant_count"] == 2
        brief = st1["tenants"]["intro_a"]
        assert brief["state"] == "running"
        assert brief["health"] == "healthy"
        assert brief["rounds_target"] == 40
        assert brief["device"]
        r1 = brief["rounds_completed"]
        _spin(lambda: a.server.round_idx > r1 + 1, "rounds advancing")
        st2 = _get(port, "/status")[1]
        assert st2["tenants"]["intro_a"]["rounds_completed"] > r1
        assert st2["uptime_s"] >= st1["uptime_s"]
        # /tenants/<name>: flight tail + health + checkpoint freshness
        status, doc = _get(port, "/tenants/intro_a")
        assert status == 200
        assert doc["tenant"] == "intro_a"
        assert doc["status"]["name"] == "intro_a"
        assert len(doc["flight"]["tail"]) >= 1
        rec = doc["flight"]["tail"][-1]
        assert {"round", "t_s", "phases"} <= set(rec)
        assert "broadcast" in rec["phases"]
        assert doc["flight"]["percentiles"]["round"]["p50"] > 0
        assert doc["health"]["clients_seen"] >= 1
        assert doc["checkpoint"]["exists"]
        assert doc["checkpoint"]["age_s"] is not None
        # /compile: the process-wide compile story
        status, comp = _get(port, "/compile")
        assert status == 200
        assert "backend_compiles" in comp and "programs" in comp
        # /healthz: every tenant non-failed -> 200
        status, hz = _get(port, "/healthz")
        assert status == 200 and hz["status"] == "ok"
        # unknown paths are 404, not a silent metrics answer
        for path in ("/nope", "/tenants/", "/status/extra"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10
                )
            assert ei.value.code == 404, path
        # unknown tenant is 404 too
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/tenants/ghost", timeout=10
            )
        assert ei.value.code == 404
        srv.drain()
        srv.wait()
    finally:
        srv.close()


def test_healthz_goes_503_when_a_tenant_fails():
    data, model = _data(), _model()

    def crash(row):
        if "t_s" in row:
            raise RuntimeError("tenant bug")

    srv = FederationServer(prom_port=0)
    srv.create_session("doomed", _cfg(comm_round=3), data, model,
                       log_fn=crash)
    srv.start()
    results = srv.wait()
    assert not results["doomed"]["ok"]
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.prom_port}/healthz", timeout=10
        )
        raise AssertionError("healthz should be 503")
    except urllib.error.HTTPError as e:
        assert e.code == 503
        doc = json.loads(e.read().decode())
        assert doc["failed_tenants"] == ["doomed"]
    srv.close()


def test_tenant_metrics_carry_device_label():
    data, model = _data(), _model()
    srv = FederationServer(prom_port=0)
    srv.create_session("dev_label", _cfg(comm_round=2), data, model)
    srv.start()
    srv.wait()
    body = srv.render_metrics()
    lines = [
        ln for ln in body.splitlines()
        if 'tenant="dev_label"' in ln
    ]
    assert lines
    assert all('device="' in ln for ln in lines), lines[:3]
    srv.close()


# ---------------------------------------------------------------------------
# exporter route table + scrape-under-churn (the satellite fix)
# ---------------------------------------------------------------------------


def test_exporter_unknown_paths_404_and_routes_answer():
    reg = MetricsRegistry()
    reg.counter("probe_total", "probe").inc()
    exp = PrometheusExporter(port=0, registry=reg)
    exp.add_route("/custom", lambda path: (200, {"hello": "world"}))
    exp.add_route("/boom", lambda path: 1 / 0)
    with exp:
        port = exp.port
        status, doc = _get(port, "/custom")
        assert (status, doc) == (200, {"hello": "world"})
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics").read().decode()
        assert "probe_total 1.0" in body
        # "/" stays a metrics alias (legacy scrape configs)
        root = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/").read().decode()
        assert "probe_total 1.0" in root
        # default healthz when no tenant-aware route overrides it
        hz = urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz")
        assert hz.status == 200
        for path in ("/anything", "/metricsx", "/custom/extra"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10)
            assert ei.value.code == 404, path
        # a raising route answers 500 without killing the server
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/boom")
        assert ei.value.code == 500
        assert urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz").status == 200


def _assert_valid_exposition(body):
    """Every sample line must parse and belong to exactly one HELP/TYPE
    block — a torn render would interleave blocks or truncate lines."""
    assert body.endswith("\n")
    seen_types = {}
    current = None
    for ln in body.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            current = ln.split()[2]
        elif ln.startswith("# TYPE "):
            name = ln.split()[2]
            assert name == current, (name, current)
            assert name not in seen_types, f"duplicate TYPE block {name}"
            seen_types[name] = True
        else:
            metric = ln.split("{", 1)[0].split(" ", 1)[0]
            base = current
            assert base is not None and metric.startswith(base), ln
            # value parses as a float
            float(ln.rsplit(" ", 1)[1])


def test_concurrent_scrape_during_tenant_churn_renders_valid_exposition():
    """The satellite fix's second half: a scrape racing add_tenant/
    remove_tenant must always serve a structurally valid exposition (no
    torn TenantedRegistryView output)."""
    base = MetricsRegistry()
    base.counter("base_total", "base").inc()
    view = TenantedRegistryView(base=base)
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            name = f"t{i % 7}"
            reg = MetricsRegistry()
            reg.counter("churn_total", "per-tenant", ("k",)).inc(k="x")
            reg.gauge("churn_gauge", "per-tenant").set(i)
            view.add_tenant(name, reg, extra={"device": "cpu"})
            if i % 3 == 0:
                view.remove_tenant(f"t{(i + 3) % 7}")
            i += 1

    with PrometheusExporter(port=0, registry=view) as exp:
        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 3.0
            scrapes = 0
            while time.monotonic() < deadline:
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/metrics", timeout=10
                ).read().decode()
                _assert_valid_exposition(body)
                scrapes += 1
            assert scrapes > 10
        finally:
            stop.set()
            t.join(timeout=10)


# ---------------------------------------------------------------------------
# status CLI
# ---------------------------------------------------------------------------


def test_render_status_table_is_aligned_and_complete():
    from fedml_tpu.serve.introspect import render_status

    doc = {
        "uptime_s": 12.3,
        "tenant_count": 2,
        "tenants": {
            "alpha": {
                "state": "running", "health": "healthy",
                "rounds_completed": 5, "rounds_target": 40,
                "restarts": 0, "current_round_age_s": 0.12,
                "rounds_per_s": 8.5, "device": "tpu",
            },
            "beta": {
                "state": "done", "health": "degraded",
                "rounds_completed": 3, "rounds_target": 3,
                "restarts": 1, "slo_breaches": {"round_s": 2},
                "device": "tpu",
            },
        },
    }
    out = render_status(doc)
    lines = out.splitlines()
    assert "2 tenant(s)" in lines[0]
    assert lines[1].startswith("TENANT")
    assert any("alpha" in ln and "5/40" in ln and "8.50" in ln
               for ln in lines)
    assert any("beta" in ln and "degraded (slo:2)" in ln for ln in lines)


def test_status_cli_against_live_service():
    from click.testing import CliRunner

    from fedml_tpu.serve.introspect import status_main

    data, model = _data(), _model()
    srv = FederationServer(prom_port=0)
    srv.create_session("cli_t", _cfg(comm_round=2), data, model)
    srv.start()
    srv.wait()
    url = f"http://127.0.0.1:{srv.prom_port}"
    r = CliRunner().invoke(status_main, ["--url", url])
    assert r.exit_code == 0, r.output
    assert "cli_t" in r.output and "TENANT" in r.output
    r = CliRunner().invoke(status_main, ["--url", url, "--tenant", "cli_t"])
    assert r.exit_code == 0, r.output
    doc = json.loads(r.output)
    assert doc["tenant"] == "cli_t"
    assert "flight" in doc
    srv.close()
    # connection errors are a clean CLI failure, not a traceback
    r = CliRunner().invoke(status_main, ["--url", url])
    assert r.exit_code != 0
    assert "could not reach" in r.output
