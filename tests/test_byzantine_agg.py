"""Byzantine-robust aggregators (robustness/robust_aggregation.py: median,
trimmed mean, Krum/Multi-Krum — beyond the reference's clip+DP): outlier
resistance of each reducer, Krum selection, and the end-to-end contract that
they defeat the boosted backdoor attack (same harness as test_backdoor)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.robustness.robust_aggregation import (
    RobustConfig,
    coordinate_median,
    krum_aggregate,
    krum_select,
    make_byzantine_aggregate,
    trimmed_mean,
)


def _stacked(C=7, shape=(4, 3), outliers=(0,), scale=100.0, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.normal(size=(C,) + shape).astype(np.float32)
    for i in outliers:
        base[i] = scale
    return {"params": {"w": jnp.asarray(base)}}, base


def test_median_resists_outlier():
    tree, base = _stacked()
    out = np.asarray(coordinate_median(tree)["params"]["w"])
    clean_median = np.median(np.delete(base, 0, axis=0), axis=0)
    # with 1 outlier of 7, the median moves at most to a neighboring order
    # statistic — nowhere near the outlier value
    assert np.abs(out).max() < 5.0
    np.testing.assert_allclose(out, np.median(base, axis=0))
    assert np.abs(out - clean_median).max() < 2.0


def test_trimmed_mean_removes_extremes():
    tree, base = _stacked()
    out = np.asarray(trimmed_mean(tree, trim_k=1)["params"]["w"])
    assert np.abs(out).max() < 5.0  # the 100.0 outlier was trimmed
    s = np.sort(base, axis=0)
    np.testing.assert_allclose(out, s[1:-1].mean(axis=0), rtol=1e-5)
    with pytest.raises(ValueError):
        trimmed_mean(tree, trim_k=4)  # 2k >= C


def test_krum_selects_honest_client():
    tree, base = _stacked(outliers=(2,))
    sel = np.asarray(krum_select(tree, num_byzantine=1, m=3))
    assert 2 not in sel
    agg = np.asarray(
        krum_aggregate(tree, num_byzantine=1, m=1)["params"]["w"]
    )
    # Krum returns one honest client's exact weights
    assert any(np.allclose(agg, base[i]) for i in range(7) if i != 2)
    with pytest.raises(ValueError):
        krum_select(tree, num_byzantine=5)


def test_bn_stats_keep_weighted_mean():
    C = 4
    w = jnp.asarray(np.arange(C * 2, dtype=np.float32).reshape(C, 2))
    stats = jnp.asarray(np.arange(C * 2, dtype=np.float32).reshape(C, 2))
    tree = {"params": {"w": w}, "batch_stats": {"bn": {"mean": stats}}}
    ns = jnp.asarray([1.0, 1.0, 1.0, 5.0])
    out = coordinate_median(tree, ns)
    np.testing.assert_allclose(
        np.asarray(out["batch_stats"]["bn"]["mean"]),
        np.tensordot(np.asarray(ns) / 8.0, np.asarray(stats), axes=1),
        rtol=1e-6,
    )


def test_config_validation():
    with pytest.raises(ValueError, match="unknown defense_type"):
        make_byzantine_aggregate(RobustConfig(defense_type="kurm"))
    # clip/noise defenses are not aggregators — None, no error
    assert make_byzantine_aggregate(RobustConfig(defense_type="weak_dp")) is None
    tree, _ = _stacked()
    with pytest.raises(ValueError, match="m <= clients"):
        # C=7, f=1 → m must be <= 4
        krum_aggregate(tree, num_byzantine=1, m=5)
    # negative f must not silently become python-slice semantics
    with pytest.raises(ValueError, match="trim_k"):
        trimmed_mean(tree, trim_k=-1)
    with pytest.raises(ValueError, match="byzantine"):
        krum_select(tree, num_byzantine=-2)
    for bad_m in (0, -1):  # empty/negative selection must not slice silently
        with pytest.raises(ValueError, match="1 <= m"):
            krum_select(tree, num_byzantine=1, m=bad_m)
    with pytest.raises(ValueError, match="num_byzantine"):
        make_byzantine_aggregate(
            RobustConfig(defense_type="median", num_byzantine=-1)
        )


@pytest.mark.parametrize("defense", ["median", "trimmed_mean", "multi_krum"])
def test_byzantine_aggregators_defeat_backdoor(defense):
    from tests.test_backdoor import _run

    main_nodef, asr_nodef = _run(RobustConfig(defense_type="no_defense"))
    assert asr_nodef > 0.5
    cfg = RobustConfig(
        defense_type=defense, num_byzantine=2, multi_krum_m=3
    )
    assert make_byzantine_aggregate(cfg) is not None
    main_def, asr_def = _run(cfg)
    assert asr_def < 0.5 * asr_nodef, (defense, asr_def, asr_nodef)
    assert main_def > 0.6, (defense, main_def)
