"""Two-level mesh hierarchical FL == host-loop hierarchical FL, and the
hybrid DCN×ICI mesh helpers (parallel/multihost.py)."""

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvgAPI, assign_groups
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.parallel.hierarchical_sharded import HierarchicalShardedAPI
from fedml_tpu.parallel.multihost import (
    devices_by_host,
    hybrid_mesh,
    initialize_multihost,
    mesh_traffic_summary,
)


def _cfg(group_num, group_comm_round, rounds=2, batch_size=4):
    return RunConfig(
        data=DataConfig(batch_size=batch_size, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=12,
            client_num_per_round=8,
            comm_round=rounds,
            epochs=1,
            group_num=group_num,
            group_comm_round=group_comm_round,
            frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        model="lr",
        seed=3,
    )


def _data():
    return synthetic_classification(
        num_clients=12,
        num_classes=5,
        feat_shape=(6,),
        samples_per_client=40,
        partition_method="hetero",
        seed=1,
    )


@pytest.mark.parametrize("group_num,group_comm_round", [(2, 2), (4, 1)])
def test_mesh_hierarchical_equals_host_loop(group_num, group_comm_round):
    """The one-program two-level round reproduces the host loop exactly
    (same sampling, stacking seeds, PRNG streams — only the execution
    strategy differs). With 4 groups and 8 sampled of 12 clients, some
    groups can be empty — exercising the zero-weight gating."""
    data = _data()
    cfg = _cfg(group_num, group_comm_round)
    groups = assign_groups(data.num_clients, group_num, seed=cfg.seed)
    model = create_model("lr", "synthetic", (6,), 5)

    host = HierarchicalFedAvgAPI(cfg, data, model, groups=groups)
    mesh = hybrid_mesh("groups", "clients", dcn_size=group_num)
    sharded = HierarchicalShardedAPI(cfg, data, model, mesh=mesh, groups=groups)

    for r in range(cfg.fed.comm_round):
        _, m_host = host.train_round(r)
        _, m_mesh = sharded.train_round(r)
        for k in ("loss_sum", "correct", "count"):
            np.testing.assert_allclose(
                float(m_host[k]), float(m_mesh[k]), rtol=1e-5, atol=1e-5
            )

    flat_host = np.concatenate(
        [np.ravel(l) for l in jax.tree_util.tree_leaves(host.global_vars)]
    )
    flat_mesh = np.concatenate(
        [np.ravel(l) for l in jax.tree_util.tree_leaves(sharded.global_vars)]
    )
    np.testing.assert_allclose(flat_host, flat_mesh, rtol=2e-5, atol=2e-5)


def test_mesh_hierarchical_full_batch():
    """batch_size=-1 (the oracle's degenerate config) resolves to one
    uniform shape across groups and still matches the host loop."""
    data = _data()
    cfg = _cfg(2, 1, batch_size=-1)
    groups = assign_groups(data.num_clients, 2, seed=cfg.seed)
    model = create_model("lr", "synthetic", (6,), 5)
    host = HierarchicalFedAvgAPI(cfg, data, model, groups=groups)
    mesh = hybrid_mesh("groups", "clients", dcn_size=2)
    sharded = HierarchicalShardedAPI(cfg, data, model, mesh=mesh, groups=groups)
    host.train_round(0)
    sharded.train_round(0)
    for a, b in zip(
        jax.tree_util.tree_leaves(host.global_vars),
        jax.tree_util.tree_leaves(sharded.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


def test_hybrid_mesh_layout():
    """Single-process: 8 CPU devices fold into the requested DCN×ICI grid;
    all axes are intra-process, so traffic summary reports ici."""
    mesh = hybrid_mesh("groups", "clients", dcn_size=2)
    assert mesh.shape == {"groups": 2, "clients": 4}
    assert mesh_traffic_summary(mesh) == {"groups": "ici", "clients": "ici"}
    grid = devices_by_host()
    assert grid.shape[0] == 1  # one process in tests
    with pytest.raises(ValueError):
        hybrid_mesh(dcn_size=3)  # 8 % 3 != 0


def test_hybrid_mesh_multi_process_layout():
    """Fabricated two-host device set: rows follow process_index, so the
    outer axis crosses DCN and the inner axis stays on ICI."""

    class FakeDev:
        def __init__(self, pid, did):
            self.process_index, self.id = pid, did

    devs = [FakeDev(p, d) for p in (1, 0) for d in (3, 1, 0, 2)]
    grid = devices_by_host(devs)
    assert [[d.process_index for d in row] for row in grid.tolist()] == [
        [0, 0, 0, 0],
        [1, 1, 1, 1],
    ]
    assert [d.id for d in grid[0]] == [0, 1, 2, 3]
    # uneven hosts are rejected
    with pytest.raises(ValueError):
        devices_by_host(devs + [FakeDev(2, 0)])


def test_initialize_multihost_noop(monkeypatch):
    """Unconfigured single-process call is a safe no-op."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert initialize_multihost() is False
    assert initialize_multihost(num_processes=1) is False
