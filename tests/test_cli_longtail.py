"""CLI completeness (VERDICT r1 #7): every algorithm package reachable from
one command, plus --resume kill-and-continue and the second-order DARTS
architect."""

import json

import numpy as np
import pytest
from click.testing import CliRunner

from fedml_tpu.cli import ALGORITHMS, main


def _invoke(args):
    result = CliRunner().invoke(main, args)
    assert result.exit_code == 0, result.output
    return json.loads(result.output.strip().splitlines()[-1])


BASE = [
    "--client_num_in_total", "3",
    "--client_num_per_round", "3",
    "--comm_round", "1",
    "--batch_size", "8",
]


@pytest.mark.parametrize(
    "algorithm,extra",
    [
        ("fedgkt", ["--dataset", "synthetic", "--lr", "0.05"]),
        ("fedgan", ["--dataset", "synthetic", "--lr", "2e-4"]),
        ("fedseg", ["--dataset", "seg_synth", "--model", "segnet", "--lr", "0.05"]),
        ("fednas", ["--dataset", "synthetic", "--batch_size", "8"]),
        ("split_nn", ["--dataset", "synthetic", "--lr", "0.1"]),
        ("vertical_fl", ["--dataset", "synthetic", "--lr", "0.05"]),
        ("decentralized", ["--dataset", "synthetic", "--lr", "0.1"]),
        ("secagg", ["--dataset", "synthetic"]),
        ("scaffold", ["--dataset", "synthetic", "--lr", "0.1"]),
    ],
)
def test_every_longtail_algorithm_reachable(algorithm, extra):
    out = _invoke(["--algorithm", algorithm] + BASE + extra)
    assert out  # one JSON row with run results
    if algorithm == "secagg":
        assert out["secure_sum_ok"] is True
        assert out["dropped"] is not None  # dropout recovery exercised


def test_cli_algorithm_tuple_is_complete():
    """Guard: every algorithms/ package is wired (the r1 gap was 6/15)."""
    assert set(ALGORITHMS) >= {
        "fedavg", "fedopt", "fedprox", "fednova", "scaffold", "hierarchical",
        "fedavg_robust", "fedgkt", "fedgan", "fedseg", "fednas",
        "split_nn", "vertical_fl", "decentralized", "secagg",
    }


def test_resume_reproduces_uninterrupted_run(tmp_path):
    """Kill-and-resume == uninterrupted: run 4 rounds straight; run 2 rounds,
    'crash', resume from the checkpoint for rounds 2-3; final accuracy and
    losses must match exactly (round-seeded sampling + restored params)."""
    common = [
        "--dataset", "synthetic", "--model", "lr",
        "--client_num_in_total", "6", "--client_num_per_round", "3",
        "--batch_size", "8", "--lr", "0.1",
        "--frequency_of_the_test", "1",
    ]
    full = _invoke(common + ["--comm_round", "4"])

    ck = str(tmp_path / "ck")
    _invoke(common + ["--comm_round", "2", "--checkpoint_path", ck])
    resumed = _invoke(
        common + ["--comm_round", "4", "--checkpoint_path", ck, "--resume"]
    )
    assert resumed["round"] == full["round"] == 3
    np.testing.assert_allclose(resumed["Test/Acc"], full["Test/Acc"], rtol=1e-6)
    np.testing.assert_allclose(resumed["Test/Loss"], full["Test/Loss"], rtol=1e-5)


def test_resume_from_midrun_crash(tmp_path, monkeypatch):
    """The periodic (test-round) checkpoint must carry 'next round to run':
    crash DURING round 2 (after round 1's save), resume, and match the
    uninterrupted run exactly — guards the r2 off-by-one where a resumed
    run re-applied an already-applied round."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    common = [
        "--dataset", "synthetic", "--model", "lr",
        "--client_num_in_total", "6", "--client_num_per_round", "3",
        "--batch_size", "8", "--lr", "0.1",
        "--frequency_of_the_test", "1",
    ]
    full = _invoke(common + ["--comm_round", "4"])

    ck = str(tmp_path / "crash_ck")
    orig = FedAvgAPI.train_round

    def crashing(self, round_idx):
        if round_idx == 2:
            raise RuntimeError("simulated kill")
        return orig(self, round_idx)

    monkeypatch.setattr(FedAvgAPI, "train_round", crashing)
    result = CliRunner().invoke(
        main, common + ["--comm_round", "4", "--checkpoint_path", ck]
    )
    assert result.exit_code != 0  # crashed mid-run as intended
    monkeypatch.setattr(FedAvgAPI, "train_round", orig)

    resumed = _invoke(
        common + ["--comm_round", "4", "--checkpoint_path", ck, "--resume"]
    )
    assert resumed["round"] == full["round"] == 3
    np.testing.assert_allclose(resumed["Test/Acc"], full["Test/Acc"], rtol=1e-6)
    np.testing.assert_allclose(resumed["Test/Loss"], full["Test/Loss"], rtol=1e-5)


def test_resume_restores_server_opt_state(tmp_path):
    """FedOpt + Adam: the server moments must survive kill-and-resume (the
    checkpoint subsystem persists opt state; the CLI must round-trip it)."""
    common = [
        "--dataset", "synthetic", "--model", "lr",
        "--client_num_in_total", "6", "--client_num_per_round", "3",
        "--batch_size", "8", "--lr", "0.1",
        "--frequency_of_the_test", "1",
        "--algorithm", "fedopt", "--server_optimizer", "adam",
        "--server_lr", "0.05",
    ]
    full = _invoke(common + ["--comm_round", "4"])
    ck = str(tmp_path / "fedopt_ck")
    _invoke(common + ["--comm_round", "2", "--checkpoint_path", ck])
    resumed = _invoke(
        common + ["--comm_round", "4", "--checkpoint_path", ck, "--resume"]
    )
    np.testing.assert_allclose(resumed["Test/Loss"], full["Test/Loss"], rtol=1e-5)
    np.testing.assert_allclose(resumed["Test/Acc"], full["Test/Acc"], rtol=1e-6)


def test_second_order_darts_differs_from_first():
    """arch_grad='second' must run and move α differently from first-order
    (the unrolled term ξ·∇²L is nonzero on a real problem)."""
    from fedml_tpu.algorithms.fednas import FedNASAPI
    from fedml_tpu.data.synthetic import synthetic_classification

    data = synthetic_classification(
        num_clients=2, num_classes=3, feat_shape=(8, 8, 3),
        samples_per_client=32, partition_method="homo", ragged=False, seed=1,
    )
    alphas = {}
    for mode in ("first", "second"):
        api = FedNASAPI(
            data, num_classes=3, input_shape=(8, 8, 3), ch=4, cells=1,
            steps=2, batch_size=8, seed=0, arch_grad=mode,
        )
        before = np.asarray(api.variables["params"]["alpha_normal"]).copy()
        api.train_round(0, client_num_per_round=2, epochs=1)
        after = np.asarray(api.variables["params"]["alpha_normal"])
        assert not np.allclose(before, after)
        alphas[mode] = after
    assert not np.allclose(alphas["first"], alphas["second"])


def test_cli_profile_dir_writes_trace(tmp_path):
    import os

    prof = tmp_path / "prof"
    _invoke(
        [
            "--dataset", "synthetic", "--model", "lr",
            "--client_num_in_total", "3", "--client_num_per_round", "3",
            "--comm_round", "1", "--batch_size", "8",
            "--profile_dir", str(prof),
        ]
    )
    # jax.profiler writes plugins/profile/<ts>/*; presence of anything is
    # the contract
    found = any(os.scandir(prof)) if prof.exists() else False
    assert found


def test_cli_backdoor_attack_reports_asr():
    """--attack backdoor end-to-end: undefended ASR is high, a tight
    clipping bound collapses it (the ref's poisoned-task eval loop,
    FedAvgRobustAggregator.py:14-60, as one CLI flag)."""
    atk = [
        "--algorithm", "fedavg_robust", "--attack", "backdoor",
        "--num_attackers", "2", "--attack_boost", "8",
        "--dataset", "synthetic", "--model", "lr",
        "--client_num_in_total", "8", "--client_num_per_round", "8",
        "--comm_round", "4", "--epochs", "1",
        "--frequency_of_the_test", "100",
    ]
    nodef = _invoke(atk + ["--defense", "no_defense"])
    clipped = _invoke(atk + ["--defense", "norm_diff_clipping",
                             "--norm_bound", "0.3"])
    assert nodef["Backdoor/ASR"] > 0.5
    assert clipped["Backdoor/ASR"] < 0.5 * nodef["Backdoor/ASR"]
    assert clipped["Test/Acc"] > 0.6


def test_cli_attack_requires_robust_vmap():
    result = CliRunner().invoke(
        main,
        ["--algorithm", "fedavg", "--attack", "backdoor"] + BASE
        + ["--dataset", "synthetic"],
    )
    assert result.exit_code != 0
    assert "fedavg_robust" in result.output
