"""Session supervisor (fedml_tpu/serve/supervisor.py): crash -> restart
from the rolling checkpoint with bit-parity, restart budgets, the
crash-loop breaker, tenant-labeled restart metrics, and the serve CLI's
split exit codes (flaky tenant vs misconfigured spec)."""

import json
import os
import urllib.request

import jax
import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.serve import (
    FederationServer,
    FedSession,
    RestartBudgetExhausted,
    RestartPolicy,
    SupervisedSession,
)


def _data(num_clients=6, seed=0):
    return synthetic_classification(
        num_clients=num_clients, num_classes=3, feat_shape=(10,),
        samples_per_client=24, partition_method="homo", seed=seed,
    )


def _model():
    return create_model("lr", "synthetic", (10,), 3)


def _sync_cfg(comm_round=6, workers=2, total=6, seed=7, **fed_kw):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=total, client_num_per_round=workers,
            comm_round=comm_round, epochs=1, frequency_of_the_test=100,
            **fed_kw,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=seed,
    )


def _async_cfg(comm_round=6, workers=1, total=6, k=1, seed=3):
    return _sync_cfg(
        comm_round=comm_round, workers=workers, total=total, seed=seed,
        async_buffer_k=k,
    )


def _tree_equal(a, b):
    for la, lb in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def _kill_once_at_round(n):
    state = {"done": False}

    def log_fn(row):
        if row.get("round") == n and "t_s" in row and not state["done"]:
            state["done"] = True
            raise RuntimeError("chaos kill")

    return log_fn


# ---------------------------------------------------------------------------
# self-healing with bit-parity
# ---------------------------------------------------------------------------


def test_sync_tenant_killed_mid_flight_recovers_bit_identical(tmp_path):
    """THE self-healing contract (acceptance a, as a test): a supervised
    sync tenant crashes once mid-flight; the supervisor restarts it from
    its rolling checkpoint and the final model is bit-identical to an
    uninterrupted run."""
    data, model = _data(), _model()
    ref = FedSession(_sync_cfg(), data, model).run()

    sup = SupervisedSession(
        _sync_cfg(), data, model, name="heal_sync",
        restart=RestartPolicy(budget=2, backoff_base_s=0.02),
        checkpoint_path=str(tmp_path / "ck"), checkpoint_every=1,
        log_fn=_kill_once_at_round(2),
    )
    server = sup.run()
    assert sup.restarts == 1 and sup.recovered
    assert sup.state == "done" and sup.health_state == "degraded"
    assert server.round_idx == 6
    _tree_equal(ref.global_vars, server.global_vars)
    row = sup.summary_row()
    assert row["supervisor/restarts"] == 1
    assert row["supervisor/recovered"] == 1
    assert row["supervisor/quarantined"] == 0


def test_fedbuff_tenant_killed_mid_flight_recovers_bit_identical(tmp_path):
    """Async twin: kill at a flush boundary, resume re-mints the
    assignment stream (the PR-9 contract) — now through the supervisor
    with no operator in the loop. K=1, k=1 keeps the pipeline
    sequential so equality is exact."""
    data, model = _data(num_clients=8), _model()
    ref = FedSession(
        _async_cfg(total=8), data, model, algorithm="fedbuff"
    ).run()
    assert ref.server_steps == 6

    state = {"done": False}

    def chaos(row):
        if row.get("server_step") == 3 and not state["done"]:
            state["done"] = True
            raise RuntimeError("chaos kill")

    sup = SupervisedSession(
        _async_cfg(total=8), data, model, name="heal_async",
        algorithm="fedbuff",
        restart=RestartPolicy(budget=2, backoff_base_s=0.02),
        checkpoint_path=str(tmp_path / "ack"), checkpoint_every=1,
        log_fn=chaos,
    )
    server = sup.run()
    assert sup.restarts == 1 and sup.recovered
    assert server.server_steps == 6
    _tree_equal(ref.global_vars, server.global_vars)


# ---------------------------------------------------------------------------
# budget exhaustion + crash-loop breaker
# ---------------------------------------------------------------------------


def test_corrupt_checkpoint_exhausts_budget_and_quarantines(tmp_path):
    """The satellite contract: a tenant whose checkpoint is corrupt must
    exhaust its restart budget and fail LOUDLY with a quarantine-style
    message — not spin — with the restarts visible in the scraped
    /metrics (tenant-labeled)."""
    data, model = _data(), _model()
    cp = str(tmp_path / "bad")
    with open(cp + ".npz", "wb") as f:
        f.write(b"definitely not an npz archive")
    srv = FederationServer(prom_port=0)
    sup = srv.create_session(
        "corrupt", _sync_cfg(), data, model,
        restart=RestartPolicy(budget=2, backoff_base_s=0.01),
        checkpoint_path=cp, checkpoint_every=1, resume=True,
    )
    srv.start()
    results = srv.wait()
    assert not results["corrupt"]["ok"]
    assert results["corrupt"]["error_kind"] == "restart_exhausted"
    assert "QUARANTINED" in results["corrupt"]["error"]
    assert "corrupt" in results["corrupt"]["error"]  # points at the ckpt
    assert sup.restarts == 2
    summary = results["corrupt"]["summary"]
    assert summary["supervisor/quarantined"] == 1
    assert summary["supervisor/health"] == "failed"
    # restarts are scrapeable, tenant-labeled, from the live exporter
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{srv.prom_port}/metrics"
    ).read().decode()
    # tenant-scoped samples also carry the device label (ROADMAP item 2
    # groundwork), so match on the tenant pair + value
    restart_lines = [
        ln for ln in body.splitlines()
        if ln.startswith("fedml_session_restarts_total{")
        and 'tenant="corrupt"' in ln
    ]
    assert restart_lines and restart_lines[0].endswith(" 2.0"), restart_lines
    assert 'device="' in restart_lines[0]
    quarantine_lines = [
        ln for ln in body.splitlines()
        if ln.startswith("fedml_session_quarantined{")
        and 'tenant="corrupt"' in ln
    ]
    assert quarantine_lines and quarantine_lines[0].endswith(" 1.0")
    srv.close()


def test_crash_loop_breaker_trips_before_budget(tmp_path):
    """A deterministic crash loop (no progress between crashes) trips the
    breaker after breaker_window restarts even when the budget would
    allow many more — more restarts cannot fix a deterministic crash."""
    data, model = _data(), _model()

    def always_crash(row):
        if "t_s" in row:
            raise RuntimeError("deterministic bug")

    sup = SupervisedSession(
        _sync_cfg(), data, model, name="loopy",
        restart=RestartPolicy(
            budget=50, backoff_base_s=0.01, breaker_window=2
        ),
        checkpoint_path=str(tmp_path / "lk"), checkpoint_every=1,
        log_fn=always_crash,
    )
    sup.start()
    with pytest.raises(RestartBudgetExhausted) as ei:
        sup.wait()
    assert ei.value.reason == "crash_loop"
    # window=2: the initial attempt + 1 restart both crashed at round 0,
    # so exactly 1 restart burned — nowhere near the 50-restart budget
    assert sup.restarts == 1
    assert "crash-loop breaker" in str(ei.value)


def test_supervised_config_error_does_not_burn_budget():
    """A deterministic session-build rejection (config guard, no
    checkpoint in play) is terminal on the FIRST attempt and classified
    'config' — retrying identical inputs cannot help, and reporting it
    as a flaky tenant (exit 3) would send the operator chasing ghosts."""
    data, model = _data(), _model()
    srv = FederationServer()
    sup = srv.create_session(
        "badsup", _sync_cfg(fault_plan='{"default": {"dropout_p": 0.5}}'),
        data, model, restart=RestartPolicy(budget=5, backoff_base_s=0.01),
    )
    srv.start()
    results = srv.wait()
    assert not results["badsup"]["ok"]
    assert results["badsup"]["error_kind"] == "config"
    assert "deadline_s" in results["badsup"]["error"]
    assert sup.restarts == 0  # the budget was not touched


def test_unsupervised_config_error_classified_config():
    """A config-guard ValueError at session build stays the
    misconfigured-spec class — distinct from a flaky tenant."""
    data, model = _data(), _model()
    srv = FederationServer()
    srv.add_session(FedSession(
        _sync_cfg(fault_plan='{"default": {"dropout_p": 0.5}}'),
        data, model, name="badcfg",
    ))
    with pytest.raises(ValueError, match="deadline_s"):
        srv.start()
    session = srv.session("badcfg")
    assert session.failure_phase == "build"


def test_supervised_tenant_without_checkpoint_restarts_from_scratch():
    data, model = _data(), _model()
    killed = {"done": False}

    def chaos(row):
        if row.get("round") == 1 and "t_s" in row and not killed["done"]:
            killed["done"] = True
            raise RuntimeError("chaos")

    ref = FedSession(_sync_cfg(comm_round=3), data, model).run()
    sup = SupervisedSession(
        _sync_cfg(comm_round=3), data, model, name="scratch",
        restart=RestartPolicy(budget=1, backoff_base_s=0.01),
        log_fn=chaos,
    )
    server = sup.run()
    assert sup.restarts == 1 and server.round_idx == 3
    _tree_equal(ref.global_vars, server.global_vars)  # deterministic rerun


def test_supervised_session_rejects_bad_config_eagerly():
    """Constructor-level config errors surface at create time — before
    any supervision — so a misconfigured spec cannot burn a restart
    budget and masquerade as flakiness."""
    data, model = _data(), _model()
    with pytest.raises(ValueError, match="warmup"):
        SupervisedSession(
            _async_cfg(), data, model, algorithm="fedbuff", warmup=True,
            restart=RestartPolicy(budget=3),
        )


def test_stop_during_backoff_fails_fast(tmp_path):
    data, model = _data(), _model()

    def always_crash(row):
        if "t_s" in row:
            raise RuntimeError("bug")

    sup = SupervisedSession(
        _sync_cfg(), data, model, name="stopme",
        restart=RestartPolicy(budget=100, backoff_base_s=30.0),
        checkpoint_path=str(tmp_path / "sk"), checkpoint_every=1,
        log_fn=always_crash,
    )
    sup.start()
    import time

    t0 = time.monotonic()
    while sup.state != "backoff" and time.monotonic() - t0 < 60:
        time.sleep(0.02)
    assert sup.state == "backoff"
    sup.stop()  # wakes the 30 s backoff sleeper immediately
    with pytest.raises(RuntimeError, match="bug"):
        sup.wait(timeout=30)
    assert sup.state == "failed"


# ---------------------------------------------------------------------------
# serve CLI: split exit codes
# ---------------------------------------------------------------------------


def _json_line(output):
    """The CLI's JSON result line (click may append error text after)."""
    for line in reversed(output.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no JSON line in output: {output!r}")


def _tenant(name, **over):
    t = {
        "name": name, "algorithm": "fedavg", "runtime": "loopback",
        "model": "lr", "dataset": "synthetic", "client_num_in_total": 6,
        "client_num_per_round": 2, "comm_round": 2, "batch_size": 8,
        "frequency_of_the_test": 100,
    }
    t.update(over)
    return t


def test_serve_cli_exit_codes_split_config_vs_flaky(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    # (2) misconfigured spec: participation faults without deadline_s is
    # a session-build config error — and it must not kill co-tenants
    spec = {"tenants": [
        _tenant("good"),
        _tenant("bad", fault_plan='{"default": {"dropout_p": 0.5}}'),
    ]}
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec))
    r = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert r.exit_code == 2, r.output
    out = _json_line(r.output)
    assert out["good"]["ok"] and not out["bad"]["ok"]
    assert out["bad"]["error_kind"] == "config"

    # (3) flaky tenant: supervised resume from a corrupt checkpoint
    # exhausts its budget -> the dedicated exit code
    cp = tmp_path / "corrupt_ck"
    (tmp_path / "corrupt_ck.npz").write_bytes(b"garbage")
    spec = {"tenants": [_tenant(
        "flaky", checkpoint_path=str(cp), checkpoint_every=1,
        resume=True, restart_budget=1, restart_backoff_s=0.01,
    )]}
    p.write_text(json.dumps(spec))
    r = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert r.exit_code == 3, r.output
    out = _json_line(r.output)
    assert out["flaky"]["error_kind"] == "restart_exhausted"
    assert out["flaky"]["supervisor/restarts"] == 1


def test_serve_cli_supervised_clean_tenant_exits_zero(tmp_path):
    """A supervised tenant that never crashes is exit 0 with
    supervisor/restarts 0 and health "healthy" in the JSON output —
    supervision itself costs nothing. (Mid-run kills are not expressible
    through a spec; "recovered after N restarts" -> exit 0 is pinned
    programmatically in the kill/recover tests above, which run through
    the same summary surface the CLI prints.)"""
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    spec = {"tenants": [_tenant(
        "calm", restart_budget=2, restart_backoff_s=0.01,
        checkpoint_path=str(tmp_path / "calm_ck"), checkpoint_every=1,
    )]}
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec))
    r = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert r.exit_code == 0, r.output
    out = _json_line(r.output)
    assert out["calm"]["ok"]
    assert out["calm"]["supervisor/restarts"] == 0
    assert out["calm"]["supervisor/health"] == "healthy"


def test_serve_spec_gets_single_run_comm_retry_guards(tmp_path):
    """Chaos without retries in a tenant spec is a parse-time config
    error (exit 2), exactly like the single-run CLI — not a mid-run
    crash that burns a supervised tenant's restart budget."""
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    spec = {"tenants": [_tenant("chaotic", send_fault_p=0.5)]}
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec))
    r = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert r.exit_code == 2, (r.exit_code, r.output)
    assert "send_retries" in r.output and "chaotic" in r.output
    # and the valid combination passes through to the tenant config
    spec = {"tenants": [_tenant(
        "retrying", send_fault_p=0.2, send_retries=4, send_backoff_s=0.002,
    )]}
    p.write_text(json.dumps(spec))
    r = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert r.exit_code == 0, r.output
    out = _json_line(r.output)
    assert out["retrying"]["ok"]
    assert out["retrying"]["comm/retries"] > 0
    assert out["retrying"]["comm/gave_up"] == 0


def test_serve_cli_rejects_restart_knobs_without_budget(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    spec = {"tenants": [_tenant("x", restart_backoff_s=1.0)]}
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec))
    r = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert r.exit_code != 0
    assert "restart_budget" in r.output
