"""bench.py --compare: the mechanical bench-to-bench regression oracle
(pure record comparison — no backend, no timing)."""

import importlib.util
import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")


def _bench_mod():
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_compare_records_builds_delta_table_and_flags_regressions():
    bench = _bench_mod()
    record = {
        "value": 40.0,
        "north_star": {"rounds_per_sec": 40.0},
        "north_star_bf16": {"rounds_per_sec": 30.0},
        "scale_1m": {"rounds_per_sec": 350.0},
        "flash_attention_s8192": {"flash_over_xla_speedup": 3.0},  # no r/s
        "process_cold_start": {"skipped": "no backend"},
    }
    baseline = {
        "value": 42.0,
        "north_star": {"rounds_per_sec": 42.0},   # -4.8% — inside tol
        "north_star_bf16": {"rounds_per_sec": 45.0},  # -33% — regression
        "scale_1m": {"rounds_per_sec": 300.0},    # +16.7% — improvement
    }
    out = bench.compare_records(record, baseline, tol_pct=10.0)
    s = out["sections"]
    assert s["north_star"]["delta_pct"] == -4.8
    assert "regressed" not in s["north_star"]
    assert s["north_star_bf16"]["delta_pct"] == -33.3
    assert s["north_star_bf16"]["regressed"]
    assert s["scale_1m"]["delta_pct"] == 16.7
    assert s["headline"]["delta_pct"] == -4.8
    # sections without comparable r/s on both sides appear without deltas
    # (flash has no rounds_per_sec; cold_start skipped this run)
    assert "flash_attention_s8192" not in s
    assert out["regressions"] and "north_star_bf16" in out["regressions"][0]
    assert out["regress_tol_pct"] == 10.0
    assert out["missing_sections"] == []
    # a section the BASELINE measured but this run lost is listed loudly
    # (not a regression — partial passes are routine under the budget)
    out2 = bench.compare_records(
        {"scale_1m": {"skipped": "wall cap"}}, baseline, tol_pct=10.0
    )
    assert out2["missing_sections"] == [
        "north_star", "north_star_bf16", "scale_1m",
    ]
    assert out2["regressions"] == []


def test_compare_records_clean_when_within_tolerance():
    bench = _bench_mod()
    record = {"value": 41.0, "north_star": {"rounds_per_sec": 41.0}}
    baseline = {"value": 42.0, "north_star": {"rounds_per_sec": 42.0}}
    out = bench.compare_records(record, baseline, tol_pct=10.0)
    assert out["regressions"] == []


def test_compare_against_unreadable_baseline_is_loud_not_fatal(tmp_path):
    bench = _bench_mod()
    out = bench._compare_against(
        {"value": 1.0}, str(tmp_path / "missing.json"), 10.0
    )
    assert "error" in out and out["regressions"] == []


def test_unreadable_baseline_fails_the_gate_not_silently_green(tmp_path):
    """A typo'd/deleted --compare path must NOT read as "no regressions"
    — the record still emits (with the error recorded), but finalize
    exits 4 so CI notices the gate never actually compared anything."""
    import time as _time

    bench = _bench_mod()
    detail = tmp_path / "detail.json"
    em = bench._Emitter(
        _time.perf_counter(), str(detail),
        compare_path=str(tmp_path / "nope.json"), regress_tol_pct=10.0,
    )
    em.update({"north_star": {"rounds_per_sec": 40.0}})
    assert em.finalize(partial=False) == 4
    rec = json.loads(detail.read_text())
    assert "error" in rec["compare"]
    assert rec["compare"]["regressions"] == []


def test_emitter_finalize_wires_compare_block_and_exit_code(
    tmp_path, capsys
):
    """The full finalize path (what the real process exits with): a
    baseline claiming impossible throughput forces a regression -> the
    record carries the compare block, the compact stdout line carries
    the regression count, and finalize returns exit code 4. Driven
    through _Emitter in-process — a real measured section is
    machine-dependent (the tiny section wall-caps on slow CPU boxes)
    and this contract is pure bookkeeping."""
    import time as _time

    bench = _bench_mod()
    baseline = tmp_path / "BENCH_prev.json"
    baseline.write_text(json.dumps({
        "value": 1e9, "north_star": {"rounds_per_sec": 1e9},
    }))
    detail = tmp_path / "detail.json"
    em = bench._Emitter(
        _time.perf_counter(), str(detail),
        compare_path=str(baseline), regress_tol_pct=10.0,
    )
    em.update({"north_star": {"rounds_per_sec": 40.0}})
    code = em.finalize(partial=False)
    assert code == 4
    rec = json.loads(detail.read_text())
    assert rec["compare"]["baseline_file"] == "BENCH_prev.json"
    assert rec["compare"]["regressions"]
    assert rec["compare"]["sections"]["north_star"]["regressed"]
    last_line = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1]
    )
    assert last_line["compare"]["regressions"] >= 1
    assert last_line["compare"]["baseline"] == "BENCH_prev.json"
    # no baseline -> no compare block, clean exit (same record otherwise)
    em2 = bench._Emitter(_time.perf_counter(), str(tmp_path / "d2.json"))
    em2.update({"north_star": {"rounds_per_sec": 40.0}})
    assert em2.finalize(partial=False) == 0
    assert "compare" not in json.loads((tmp_path / "d2.json").read_text())


def test_bench_cli_parses_compare_flags():
    """argparse wiring smoke: --help documents the new flags without
    touching a backend (jax imports only after the probe)."""
    p = subprocess.run(
        [sys.executable, _BENCH, "--help"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert p.returncode == 0, p.stderr[-500:]
    assert "--compare" in p.stdout and "--regress_tol" in p.stdout
