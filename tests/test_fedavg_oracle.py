"""The metamorphic correctness oracle carried over from the reference's CI
(CI-script-fedavg.sh:42-58): with full batch (batch_size=-1), one local epoch,
and all clients participating, FedAvg must equal centralized full-batch SGD —
because the sample-weighted average of per-client gradients IS the centralized
gradient. Deterministic PRNG + CPU float32 makes this near-exact here (the
reference asserts to 3 decimals via wandb-summary.json)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.train.losses import masked_softmax_ce


NUM_CLIENTS = 8
NUM_CLASSES = 5
FEAT = (6,)


def _make_data():
    return synthetic_classification(
        num_clients=NUM_CLIENTS,
        num_classes=NUM_CLASSES,
        feat_shape=FEAT,
        samples_per_client=20,
        partition_method="homo",
        ragged=True,
        seed=42,
    )


def _make_model():
    return ModelDef(
        module=LogisticRegression(num_classes=NUM_CLASSES),
        input_shape=FEAT,
        num_classes=NUM_CLASSES,
        name="lr",
    )


def _centralized_sgd(model, data, lr, rounds):
    """Full-batch centralized GD, `rounds` steps."""
    x, y = data.centralized_train()
    x, y = jnp.asarray(x), jnp.asarray(y)
    mask = jnp.ones(x.shape[0])
    variables = model.init(jax.random.fold_in(jax.random.PRNGKey(0), 0))
    params = variables["params"]

    def loss_fn(p):
        logits, _ = model.apply({"params": p}, x, train=True)
        return masked_softmax_ce(logits, y, mask)

    for _ in range(rounds):
        g = jax.grad(loss_fn)(params)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, g)
    return params


@pytest.mark.parametrize("rounds", [1, 5])
def test_federated_equals_centralized(rounds):
    data = _make_data()
    model = _make_model()
    lr = 0.1
    config = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=NUM_CLIENTS,
            comm_round=rounds,
            epochs=1,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=lr),
        seed=0,
    )
    api = FedAvgAPI(config, data, model)
    api.train()
    fed_params = api.global_vars["params"]
    cen_params = _centralized_sgd(model, data, lr, rounds)
    for a, b in zip(
        jax.tree_util.tree_leaves(fed_params), jax.tree_util.tree_leaves(cen_params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_fedavg_learns_synthetic():
    """End-to-end smoke: accuracy on separable synthetic data improves well
    above chance (ref CI smoke tests, CI-script-fedavg.sh:33-39)."""
    data = _make_data()
    model = _make_model()
    config = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=4,
            comm_round=20,
            epochs=2,
            frequency_of_the_test=20,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
    )
    api = FedAvgAPI(config, data, model)
    final = api.train()
    assert final["Test/Acc"] > 0.5


def test_client_sampling_parity():
    """Sampling must match the reference exactly (np.random.seed(round_idx),
    FedAVGAggregator.py:80-88)."""
    from fedml_tpu.algorithms.fedavg import client_sampling

    np.random.seed(3)
    expect = np.random.choice(range(100), 10, replace=False)
    got = client_sampling(3, 100, 10)
    assert np.array_equal(got, expect)
    # full participation returns all clients
    assert np.array_equal(client_sampling(0, 5, 5), np.arange(5))


def test_scan_and_vmap_client_schedules_agree():
    """The two client schedules are THE SAME math executed in different
    orders (scan: one client's full local run at a time, full-size
    matmuls; vmap: all clients batched). The flagship bench row rides the
    scan schedule for its MXU tiling (docs/PERF_R5.md §1 — 0.77 vs 0.42
    device MFU on the transformer LM), so their numerical agreement is a
    load-bearing contract, not an implementation detail."""
    import dataclasses

    import jax
    import numpy as np

    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model

    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(6,), samples_per_client=16,
        partition_method="hetero", ragged=False, seed=0,
    )
    model = create_model("lr", "synthetic", (6,), 3)
    base = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=8, client_num_per_round=5, comm_round=3,
            epochs=2, frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="adam", lr=0.05),
        seed=0,
    )
    apis = {}
    for sched in ("vmap", "scan"):
        cfg = dataclasses.replace(
            base, fed=dataclasses.replace(base.fed, client_parallelism=sched)
        )
        api = FedAvgAPI(cfg, data, model)
        assert api._client_mode == sched
        for r in range(3):
            api.train_round(r)
        apis[sched] = api
    for a, b in zip(
        jax.tree_util.tree_leaves(apis["vmap"].global_vars),
        jax.tree_util.tree_leaves(apis["scan"].global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )
