"""Long-tail algorithms: decentralized gossip, split learning, vertical FL,
secure aggregation — each tested against an exact oracle where one exists
(split/vfl: fused autodiff == explicit message-boundary math; secagg:
masked aggregate == plain sum; gossip: mixing preserves the mean on
doubly-stochastic topologies)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression


def test_decentralized_dsgd_regret_decreases():
    from fedml_tpu.algorithms.decentralized import DecentralizedAPI
    from fedml_tpu.partition.topology import SymmetricTopologyManager

    N, T, D = 8, 200, 6
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=D)
    x = rng.normal(size=(N, T, D)).astype(np.float32)
    y = (x @ w_true > 0).astype(np.float32)

    topo = SymmetricTopologyManager(N, neighbor_num=4)
    topo.generate_topology()
    model = ModelDef(LogisticRegression(num_classes=1), (D,), 1, name="lr")
    api = DecentralizedAPI(model, topo, lr=0.3, variant="dsgd")
    out = api.run(x, y)
    assert out["regret"][-1] < out["regret"][10] * 0.8
    # consensus: workers close to each other after mixing
    leaves = jax.tree_util.tree_leaves(api.params)
    spread = max(float(jnp.max(jnp.std(l, axis=0))) for l in leaves)
    assert spread < 0.5


def test_decentralized_pushsum_runs():
    from fedml_tpu.algorithms.decentralized import DecentralizedAPI
    from fedml_tpu.partition.topology import AsymmetricTopologyManager

    N, T, D = 6, 100, 4
    rng = np.random.default_rng(1)
    x = rng.normal(size=(N, T, D)).astype(np.float32)
    y = rng.integers(0, 2, size=(N, T)).astype(np.float32)
    topo = AsymmetricTopologyManager(N, undirected_neighbor_num=2, seed=3)
    topo.generate_topology()
    model = ModelDef(LogisticRegression(num_classes=1), (D,), 1, name="lr")
    api = DecentralizedAPI(model, topo, lr=0.1, variant="pushsum")
    out = api.run(x, y)
    assert np.isfinite(out["losses"]).all()


def test_split_nn_boundary_matches_fused():
    """The explicit acts/acts-grad exchange must produce the same gradients
    as differentiating straight through the composition."""
    from fedml_tpu.algorithms.split_nn import SplitNNAPI, split_step_with_boundary

    import flax.linen as nn

    class Bottom(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.relu(nn.Dense(8)(x))

    class Top(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(3)(x)

    bottom = ModelDef(Bottom(), (5,), 3, name="bottom")
    top = ModelDef(Top(), (8,), 3, name="top")
    api = SplitNNAPI(bottom, top, lr=0.1, seed=0)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(16, 5)), jnp.float32)
    y = jnp.asarray(np.random.default_rng(3).integers(0, 3, 16))

    loss_b, bottom_grads, top_grads = split_step_with_boundary(
        bottom, top, api.bottom_vars, api.top_vars, x, y
    )

    def fused(params):
        acts, _ = bottom.apply({"params": params["bottom"]}, x, train=True)
        logits, _ = top.apply({"params": params["top"]}, acts, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    fused_grads = jax.grad(fused)(
        {"bottom": api.bottom_vars["params"], "top": api.top_vars["params"]}
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(fused_grads["bottom"]),
        jax.tree_util.tree_leaves(bottom_grads),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(fused_grads["top"]),
        jax.tree_util.tree_leaves(top_grads),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_split_nn_ring_learns():
    from fedml_tpu.algorithms.split_nn import SplitNNAPI

    import flax.linen as nn

    class Bottom(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.relu(nn.Dense(16)(x))

    class Top(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(x)

    rng = np.random.default_rng(4)
    means = rng.normal(0, 2.0, size=(4, 6))
    clients = []
    for _ in range(3):
        y = rng.integers(0, 4, 64)
        x = (means[y] + rng.normal(0, 0.5, (64, 6))).astype(np.float32)
        clients.append((x, y))
    yt = rng.integers(0, 4, 64)
    xt = (means[yt] + rng.normal(0, 0.5, (64, 6))).astype(np.float32)

    api = SplitNNAPI(
        ModelDef(Bottom(), (6,), 4, name="b"), ModelDef(Top(), (16,), 4, name="t"), lr=0.1
    )
    for _ in range(5):
        api.train_ring(clients, batch_size=16)
    assert api.evaluate(xt, yt) > 0.7


def test_vfl_guest_host_split_matches_fused():
    from fedml_tpu.algorithms.vertical_fl import VFLAPI

    rng = np.random.default_rng(5)
    api = VFLAPI(feature_splits=(4, 3, 5), hidden_dim=6, lr=0.1, seed=0)
    xs = [rng.normal(size=(10, d)).astype(np.float32) for d in (4, 3, 5)]
    y = rng.integers(0, 2, 10).astype(np.float32)
    # explicit per-party grads (what the wire carries)
    party_grads = api.guest_host_split_step(xs, y)

    def fused(all_params):
        total = sum(
            p.contribution(pp, jnp.asarray(x))
            for p, pp, x in zip(api.parties, all_params, xs)
        )
        return optax.sigmoid_binary_cross_entropy(
            total.reshape(-1), jnp.asarray(y)
        ).mean()

    fused_grads = jax.grad(fused)(api.params)
    for pg, fg in zip(party_grads, fused_grads):
        for a, b in zip(
            jax.tree_util.tree_leaves(pg), jax.tree_util.tree_leaves(fg)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_vfl_learns():
    from fedml_tpu.algorithms.vertical_fl import VFLAPI

    rng = np.random.default_rng(6)
    D = (5, 4)
    w = [rng.normal(size=d) for d in D]
    xs = [rng.normal(size=(512, d)).astype(np.float32) for d in D]
    y = ((xs[0] @ w[0] + xs[1] @ w[1]) > 0).astype(np.float32)
    api = VFLAPI(feature_splits=D, hidden_dim=8, lr=0.1, seed=1)
    for _ in range(8):
        out = api.train_epoch(xs, y, batch_size=64)
    assert out["acc"] > 0.85


def test_bgw_share_reconstruct():
    from fedml_tpu.secagg import bgw_decode, bgw_encode

    rng = np.random.default_rng(7)
    X = rng.integers(0, 1000, size=(3, 4)).astype(np.int64)
    N, T = 7, 2
    shares = bgw_encode(X, N, T, rng=rng)
    # any T+1 distinct shares reconstruct
    for idx in ([0, 3, 6], [1, 2, 4]):
        rec = bgw_decode(shares[idx], idx)
        np.testing.assert_array_equal(rec, X)


def test_bgw_large_n_t_no_overflow():
    """N=40, T=13 puts naive np.power(alphas, T) past 2^63 — the Vandermonde
    must be built mod p or shares silently corrupt (round-1 advisor find)."""
    from fedml_tpu.secagg import bgw_decode, bgw_encode

    rng = np.random.default_rng(11)
    X = rng.integers(0, 100000, size=(2, 3)).astype(np.int64)
    N, T = 40, 13
    shares = bgw_encode(X, N, T, rng=rng)
    idx = list(range(20, 20 + T + 1))
    np.testing.assert_array_equal(bgw_decode(shares[idx], idx), X)


def test_pushsum_debiased_average_on_directed_topology():
    """With lr=0 the run is pure mixing: Push-Sum's x/ω must converge to the
    true average of initial params even on an asymmetric (directed)
    topology, which requires column-stochastic (Wᵀ) mixing — row-stochastic
    W does not conserve the sum (round-1 advisor find)."""
    from fedml_tpu.algorithms.decentralized import DecentralizedAPI
    from fedml_tpu.partition.topology import AsymmetricTopologyManager

    N, T, D = 6, 300, 4
    rng = np.random.default_rng(5)
    x = rng.normal(size=(N, T, D)).astype(np.float32)
    y = rng.integers(0, 2, size=(N, T)).astype(np.float32)
    topo = AsymmetricTopologyManager(N, undirected_neighbor_num=2, seed=3)
    topo.generate_topology()
    model = ModelDef(LogisticRegression(num_classes=1), (D,), 1, name="lr")
    api = DecentralizedAPI(model, topo, lr=0.0, variant="pushsum")
    target = jax.tree_util.tree_map(
        lambda p: np.asarray(p).mean(axis=0), api.params
    )
    api.run(x, y)
    for got, want in zip(
        jax.tree_util.tree_leaves(api.params), jax.tree_util.tree_leaves(target)
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.broadcast_to(want, got.shape), atol=1e-3
        )


def test_lcc_encode_decode():
    from fedml_tpu.secagg import lcc_decode_with_points, lcc_encode_with_points

    rng = np.random.default_rng(8)
    K, m, d = 3, 2, 5
    X = rng.integers(0, 999, size=(K, m, d)).astype(np.int64)
    beta = list(range(1, K + 1))
    alpha = list(range(10, 17))
    enc = lcc_encode_with_points(X, alpha, beta)
    dec = lcc_decode_with_points(enc[:4], alpha[:4], beta)
    np.testing.assert_array_equal(dec, X)


def test_secure_aggregation_equals_plain_sum():
    from fedml_tpu.secagg import SecureAggregator

    rng = np.random.default_rng(9)
    N, D = 5, 32
    xs = [rng.normal(size=D).astype(np.float32) for _ in range(N)]
    agg = SecureAggregator(N, D, seed=0)
    active = list(range(N))
    uploads = {i: agg.client_upload(i, xs[i], active) for i in active}
    # masked uploads are NOT the raw values
    assert not np.allclose(uploads[0], np.round(xs[0] * (1 << 16)))
    total = agg.aggregate(uploads, active)
    np.testing.assert_allclose(total, np.sum(xs, axis=0), atol=1e-3)


def test_secure_aggregation_dropout_recovery():
    from fedml_tpu.secagg import SecureAggregator

    rng = np.random.default_rng(10)
    N, D = 5, 16
    xs = [rng.normal(size=D).astype(np.float32) for _ in range(N)]
    agg = SecureAggregator(N, D, seed=1)
    active = list(range(N))
    uploads = {i: agg.client_upload(i, xs[i], active) for i in active}
    del uploads[2]  # client 2 drops after masking
    total = agg.aggregate(uploads, intended=active)
    expect = np.sum([x for i, x in enumerate(xs) if i != 2], axis=0)
    np.testing.assert_allclose(total, expect, atol=1e-3)
