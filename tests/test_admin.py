"""Admin control plane (serve/admin.py, serve/admission.py, the
method-aware exporter route table): bearer auth, 405 on wrong verbs,
live tenant add/drain/stop/reload over HTTP, measured admission
pricing + refusals with priced reasons, concurrent admin writes racing
a /metrics scrape, and the bounded per-tenant health registry under a
large-population tenant."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from test_introspect import _assert_valid_exposition

from fedml_tpu.config import (
    AdminConfig,
    DataConfig,
    FedConfig,
    PopulationConfig,
    RunConfig,
    TrainConfig,
)
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.serve import AdmissionController, FederationServer
from fedml_tpu.telemetry import MetricsRegistry

TOKEN = "test-admin-token"


def _data(num_clients=6, feat=10, seed=0):
    return synthetic_classification(
        num_clients=num_clients, num_classes=3, feat_shape=(feat,),
        samples_per_client=24, partition_method="homo", seed=seed,
    )


def _model(feat=10):
    return create_model("lr", "synthetic", (feat,), 3)


def _cfg(comm_round=3, num_clients=6, per_round=3, seed=0, admin=None,
         population=None):
    kw = {}
    if admin is not None:
        kw["admin"] = admin
    if population is not None:
        kw["population"] = population
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=num_clients, client_num_per_round=per_round,
            comm_round=comm_round, epochs=1, frequency_of_the_test=100,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=seed,
        **kw,
    )


def _spec(name, comm_round=2):
    """A minimal tenant spec for POST /tenants (single-run CLI keys).
    Every spec is the same model family on purpose: added tenants adopt
    the resident's compiled programs (the PR-9 sharing gate)."""
    return {
        "name": name, "comm_round": comm_round, "client_num_in_total": 6,
        "client_num_per_round": 3, "batch_size": 8, "epochs": 1,
    }


def _req(port, path, method="GET", body=None, token=None, timeout=30):
    """(status, parsed-json-or-text) without raising on HTTP errors."""
    data = None
    headers = {}
    if body is not None:
        data = json.dumps(body).encode() if isinstance(body, dict) else body
        headers["Content-Type"] = "application/json"
    if token is not None:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, headers=headers,
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw, status, hdrs = resp.read(), resp.status, dict(resp.headers)
    except urllib.error.HTTPError as e:
        raw, status, hdrs = e.read(), e.code, dict(e.headers)
    try:
        return status, json.loads(raw.decode()), hdrs
    except (ValueError, UnicodeDecodeError):
        return status, raw.decode(errors="replace"), hdrs


def _spin(pred, what, timeout=60.0):
    t0 = time.monotonic()
    while not pred():
        assert time.monotonic() - t0 < timeout, f"timed out: {what}"
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# admission controller: measured pricing + deterministic refusals
# ---------------------------------------------------------------------------


def test_admission_controller_prices_and_refuses_deterministically():
    reg = MetricsRegistry()
    ctl = AdmissionController(max_tenants=2, registry=reg)
    cfg, model = _cfg(), _model()
    # under the cap: admitted, with the measured price card attached
    d = ctl.decide("a", cfg, model, live_tenants=1)
    assert d.admit and d.tenant == "a"
    assert d.priced["rss_mb"] is None or d.priced["rss_mb"] > 0
    assert "local_train_digest" in d.priced
    assert "warm_in_process" in d.priced
    # at the cap: refused with the cap in the reason
    d = ctl.decide("b", cfg, model, live_tenants=2)
    assert not d.admit and "max_tenants=2" in d.reason
    # process RSS is always over a 1 MB budget: deterministic refusal
    rss = AdmissionController(max_rss_mb=1.0, registry=reg)
    d = rss.decide("c", cfg, model)
    assert not d.admit and "max_rss_mb=1" in d.reason
    # a tenant DECLARING absurd headroom is refused with the priced gap
    need = AdmissionController(registry=reg)
    cfg_hungry = _cfg(admin=AdminConfig(admit_min_headroom_mb=1e12))
    d = need.decide("d", cfg_hungry, model)
    assert not d.admit and "admit_min_headroom_mb" in d.reason
    assert d.priced["headroom_mb"] is not None
    # every decision landed in the bounded log + the counter
    snap = ctl.snapshot()
    assert snap["admitted"] == 1 and snap["refused"] == 1
    assert [x["decision"] for x in snap["decisions"]] == ["admit", "refuse"]
    body = reg.render()
    assert 'fedml_admission_total{decision="admit"} 1.0' in body
    assert 'fedml_admission_total{decision="refuse"} 3.0' in body


def test_admission_probes_warm_program_digest_of_co_tenant_family():
    """The compile-cost signal: once a same-family co-tenant owns the
    shared local-train program, an identical candidate prices as warm
    (cache_hit_p=1.0, compile ~0) through the SAME key fields the
    factory digests — the one-definition contract."""
    from fedml_tpu.algorithms.fedavg_transport import (
        local_train_key_fields,
        shared_local_train,
    )
    from fedml_tpu.compile import program_digest

    cfg, model = _cfg(seed=7), _model(feat=9)
    ctl = AdmissionController(registry=MetricsRegistry())
    before = ctl.price(cfg, model)
    digest = program_digest(local_train_key_fields(model, cfg, "classification"))
    assert before["local_train_digest"] == digest[:16]
    # register the family's program (what a co-tenant's build does)
    shared_local_train(model, cfg, "classification")
    after = ctl.price(cfg, model)
    assert after["warm_in_process"] is True
    assert after["cache_hit_p"] == 1.0
    d = ctl.decide("warm", cfg, model)
    assert d.admit and "warm in process" in d.reason


# ---------------------------------------------------------------------------
# the write surface: auth + verbs
# ---------------------------------------------------------------------------


def test_admin_routes_require_bearer_token_and_reject_get():
    data, model = _data(), _model()
    srv = FederationServer(prom_port=0, admin_token=TOKEN)
    srv.create_session("auth_t", _cfg(comm_round=2), data, model)
    srv.start()
    port = srv.prom_port
    try:
        # a GET scrape of a mutating route is 405 BEFORE any handler
        # (even a valid token cannot make GET mutate)
        status, doc, hdrs = _req(port, "/tenants", token=TOKEN)
        assert status == 405, doc
        assert "POST" in hdrs.get("Allow", "")
        # POST on the read-only surfaces is 405 too
        for path in ("/metrics", "/status", "/compile"):
            status, _, _ = _req(port, path, method="POST", body={})
            assert status == 405, path
        # no token / wrong token -> 401, nothing mutates
        for tok in (None, "wrong"):
            status, doc, _ = _req(
                port, "/tenants", method="POST", body=_spec("sneak"),
                token=tok,
            )
            assert status == 401, doc
            status, _, _ = _req(
                port, "/tenants/auth_t/stop", method="POST", body=b"",
                token=tok,
            )
            assert status == 401
        assert srv.session("auth_t").state != "stopped"
        with pytest.raises(KeyError):
            srv.session("sneak")
        srv.wait()
    finally:
        srv.close()


def test_service_without_token_has_no_write_surface():
    data, model = _data(), _model()
    srv = FederationServer(prom_port=0)  # read-only: no admin_token
    srv.create_session("ro_t", _cfg(comm_round=2), data, model)
    srv.start()
    try:
        status, _, _ = _req(
            srv.prom_port, "/tenants", method="POST", body=_spec("x"),
            token=TOKEN,
        )
        # the route is never installed: 404, not 401/405
        assert status == 404
        srv.wait()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# live lifecycle over HTTP: add / drain / stop / reload
# ---------------------------------------------------------------------------


def test_admin_add_drain_reload_lifecycle(tmp_path):
    data, model = _data(), _model()
    srv = FederationServer(
        prom_port=0, admin_token=TOKEN, admission=AdmissionController(),
    )
    # a long-lived co-tenant that stays up while we mutate around it
    srv.create_session(
        "resident", _cfg(comm_round=400), data, model,
        restart=2, checkpoint_path=str(tmp_path / "ck"), checkpoint_every=50,
    )
    srv.start()
    port = srv.prom_port
    try:
        # live ADD: the spec body is the serve CLI's tenant-spec keys
        status, doc, _ = _req(
            port, "/tenants", method="POST", body=_spec("added"),
            token=TOKEN,
        )
        assert status == 201, doc
        assert doc["tenant"] == "added"
        assert doc["admission"]["decision"] == "admit"
        added = srv.session("added")
        assert added.state == "running"
        added.wait(120)  # state flips to done at finalize, not mid-run
        assert added.state == "done"
        # duplicate name -> 409
        status, doc, _ = _req(
            port, "/tenants", method="POST", body=_spec("added"), token=TOKEN,
        )
        assert status == 409, doc
        # malformed bodies / specs -> 400, no tenant appears
        for bad in (b"{not json", {"comm_round": 2}, _spec("bad") | {
                "nonsense_key": 1}):
            status, doc, _ = _req(
                port, "/tenants", method="POST", body=bad, token=TOKEN,
            )
            assert status == 400, doc
        # hot-reload SLOs on the resident without touching co-tenants
        status, doc, _ = _req(
            port, "/tenants/resident/reload", method="POST",
            body={"slo_round_s": 45.0, "restart_budget": 5}, token=TOKEN,
        )
        assert status == 200, doc
        assert doc["applied"] == {"slo_round_s": 45.0, "restart_budget": 5}
        resident = srv.session("resident")
        assert resident.restart.budget == 5
        # non-reloadable key -> 400, nothing applied
        status, doc, _ = _req(
            port, "/tenants/resident/reload", method="POST",
            body={"comm_round": 9}, token=TOKEN,
        )
        assert status == 400 and "non-reloadable" in doc["error"]
        # restart_budget on an unsupervised tenant -> 400
        status, doc, _ = _req(
            port, "/tenants/added/reload", method="POST",
            body={"restart_budget": 9}, token=TOKEN,
        )
        assert status == 400 and "not supervised" in doc["error"]
        # reload is all-or-nothing: a malformed budget in a MIXED body
        # must not leave the new SLOs live behind the 400
        status, doc, _ = _req(
            port, "/tenants/resident/reload", method="POST",
            body={"slo_round_s": 0.5, "restart_budget": "five"},
            token=TOKEN,
        )
        assert status == 400 and "restart_budget" in doc["error"]
        wd = resident.scope.slo_watchdog  # the earlier reload created it
        assert wd.policy.round_s == 45.0  # ... and the bad one kept it
        assert resident.restart.budget == 5  # the earlier reload's value
        # unknown tenant / unknown action -> 404
        status, _, _ = _req(
            port, "/tenants/ghost/drain", method="POST", body=b"",
            token=TOKEN,
        )
        assert status == 404
        status, _, _ = _req(
            port, "/tenants/resident/explode", method="POST", body=b"",
            token=TOKEN,
        )
        assert status == 404
        # DRAIN the resident mid-flight: open round completes, state done
        status, doc, _ = _req(
            port, "/tenants/resident/drain", method="POST", body=b"",
            token=TOKEN,
        )
        assert status == 202 and doc["action"] == "drain"
        results = srv.wait(timeout=120)
        assert results["resident"]["ok"], results["resident"]
        assert results["added"]["ok"]
        # the decisions are the /status admission section
        status, st, _ = _req(port, "/status")
        assert status == 200
        assert st["admin_api"] == "enabled"
        assert st["admission"]["admitted"] >= 1
        assert any(
            d["tenant"] == "added" and d["decision"] == "admit"
            for d in st["admission"]["decisions"]
        )
    finally:
        srv.close()


def test_admission_refusal_over_http_carries_priced_reason():
    data, model = _data(), _model()
    srv = FederationServer(
        prom_port=0, admin_token=TOKEN,
        admission=AdmissionController(max_tenants=1),
    )
    srv.create_session("only", _cfg(comm_round=300), data, model)
    srv.start()
    port = srv.prom_port
    try:
        status, doc, _ = _req(
            port, "/tenants", method="POST", body=_spec("excess"),
            token=TOKEN,
        )
        assert status == 409, doc
        assert "max_tenants=1" in doc["error"]
        assert doc["decision"]["decision"] == "refuse"
        assert doc["decision"]["priced"]  # the price card rode along
        with pytest.raises(KeyError):
            srv.session("excess")
        # the refusal is queryable on /status afterwards — the operator's
        # "why was my tenant refused" answer
        _, st, _ = _req(port, "/status")
        refusals = [
            d for d in st["admission"]["decisions"]
            if d["tenant"] == "excess"
        ]
        assert refusals and "max_tenants=1" in refusals[-1]["reason"]
        assert st["admission"]["refused"] == 1
        # ... and on /metrics as the service-level counter
        assert 'fedml_admission_total{decision="refuse"} 1.0' in (
            srv.render_metrics()
        )
        _req(port, "/tenants/only/stop", method="POST", body=b"",
             token=TOKEN)
        srv.wait(timeout=60)
    finally:
        srv.close()


def test_admin_add_whose_build_fails_at_start_is_400_and_name_reusable():
    """A spec that parses and constructs but whose session BUILD rejects
    the config at start (participation faults without deadline_s) must
    answer 400 — not 500 — and unregister the tenant, so the corrected
    spec can immediately reuse the name."""
    data, model = _data(), _model()
    srv = FederationServer(prom_port=0, admin_token=TOKEN)
    srv.create_session("anchor", _cfg(comm_round=2), data, model)
    srv.start()
    port = srv.prom_port
    try:
        bad = _spec("latefail") | {
            "fault_plan": '{"default": {"dropout_p": 0.5}}'
        }
        status, doc, _ = _req(
            port, "/tenants", method="POST", body=bad, token=TOKEN,
        )
        assert status == 400, doc
        assert "deadline" in doc["error"]
        with pytest.raises(KeyError):
            srv.session("latefail")
        # corrected spec, same name: admitted
        status, doc, _ = _req(
            port, "/tenants", method="POST",
            body=bad | {"deadline_s": 30.0}, token=TOKEN,
        )
        assert status == 201, doc
        srv.wait(timeout=120)
    finally:
        srv.close()


def test_negative_content_length_cannot_hang_a_handler_thread():
    """Content-Length: -1 must be clamped, not passed to read(-1) —
    which would block the handler until client EOF, before auth runs."""
    import http.client

    data, model = _data(), _model()
    srv = FederationServer(prom_port=0, admin_token=TOKEN)
    srv.create_session("neg_t", _cfg(comm_round=2), data, model)
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.prom_port,
                                          timeout=10)
        conn.putrequest("POST", "/tenants")
        conn.putheader("Content-Length", "-1")
        conn.endheaders()  # no body, socket stays open
        resp = conn.getresponse()  # must answer promptly (401: no token)
        assert resp.status == 401
        conn.close()
        srv.wait()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# satellite: concurrent admin WRITES racing a /metrics scrape
# ---------------------------------------------------------------------------


def test_concurrent_admin_writes_racing_scrape_never_tear_or_500():
    """Extends the PR-12 scrape-under-churn satellite to the WRITE path:
    live HTTP adds/drains and reload writes racing a scrape loop must
    always render a structurally valid exposition and never 500."""
    data, model = _data(), _model()
    srv = FederationServer(
        prom_port=0, admin_token=TOKEN, admission=AdmissionController(),
    )
    srv.create_session("spine", _cfg(comm_round=2000), data, model)
    srv.start()
    port = srv.prom_port
    failures: list = []
    stop = threading.Event()

    def reload_hammer():
        i = 0
        while not stop.is_set():
            status, doc, _ = _req(
                port, "/tenants/spine/reload", method="POST",
                body={"slo_round_s": float(10 + (i % 5))}, token=TOKEN,
            )
            if status != 200:
                failures.append(("reload", status, doc))
            i += 1

    def churn_tenants():
        for i in range(3):
            name = f"churn{i}"
            status, doc, _ = _req(
                port, "/tenants", method="POST",
                body=_spec(name, comm_round=200), token=TOKEN,
            )
            if status != 201:
                failures.append(("add", status, doc))
                continue
            status, doc, _ = _req(
                port, f"/tenants/{name}/drain", method="POST", body=b"",
                token=TOKEN,
            )
            if status != 202:
                failures.append(("drain", status, doc))

    threads = [
        threading.Thread(target=reload_hammer, daemon=True),
        threading.Thread(target=churn_tenants, daemon=True),
    ]
    try:
        for t in threads:
            t.start()
        scrapes = 0
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline and threads[1].is_alive():
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30
            ).read().decode()
            _assert_valid_exposition(body)
            status, _, _ = _req(port, "/status")
            assert status == 200
            scrapes += 1
        threads[1].join(timeout=120)
        stop.set()
        threads[0].join(timeout=30)
        assert not failures, failures[:5]
        assert scrapes > 5
        assert not threads[1].is_alive(), "tenant churn never finished"
        _req(port, "/tenants/spine/stop", method="POST", body=b"",
             token=TOKEN)
        results = srv.wait(timeout=120)
        for i in range(3):
            assert results[f"churn{i}"]["ok"], results[f"churn{i}"]
    finally:
        stop.set()
        srv.close()


# ---------------------------------------------------------------------------
# satellite: the status printer reflects placement + admission
# ---------------------------------------------------------------------------


def test_render_status_shows_slice_column_and_admission_sections():
    from fedml_tpu.serve.introspect import render_status

    doc = {
        "uptime_s": 5.0, "tenant_count": 2,
        "tenants": {
            "pinned": {"state": "running", "health": "healthy",
                       "rounds_completed": 3, "rounds_target": 10,
                       "device": "cpu:0-3"},
            "packed": {"state": "running", "health": "healthy",
                       "rounds_completed": 1, "rounds_target": 10,
                       "device": "cpu:4-7"},
        },
        "placement": {
            "cpu:0-3": {"devices": 4, "tenants": ["pinned"], "cost": 1.5},
            "cpu:4-7": {"devices": 4, "tenants": ["packed"], "cost": 0},
        },
        "admission": {
            "admitted": 2, "refused": 1,
            "decisions": [
                {"tenant": "ghost", "decision": "refuse",
                 "reason": "tenant cap: 2 live tenants >= max_tenants=2"},
            ],
        },
    }
    out = render_status(doc)
    # the DEVICE column carries the SLICE label per tenant row
    assert any("pinned" in ln and "cpu:0-3" in ln for ln in out.splitlines())
    assert any("packed" in ln and "cpu:4-7" in ln for ln in out.splitlines())
    assert "placement:" in out
    assert any("cpu:0-3" in ln and "pinned" in ln and "cost 1.5" in ln
               for ln in out.splitlines())
    assert "admission: 2 admitted, 1 refused" in out
    assert any("refuse" in ln and "ghost" in ln and "max_tenants=2" in ln
               for ln in out.splitlines())


# ---------------------------------------------------------------------------
# satellite: large-population tenant with the bounded health registry
# ---------------------------------------------------------------------------


def test_large_population_tenant_health_registry_stays_bounded():
    """Serve x population item-1 remainder: a tenant whose population is
    far larger than its health-registry bound keeps the per-tenant
    ACTIVE record set at the bound (full timing windows only for the
    bounded LRU; evicted clients spill to compact counters), while a
    co-tenant with the default bound is untouched."""
    bound = 8
    big_cfg = _cfg(
        comm_round=6, num_clients=64, per_round=16,
        population=PopulationConfig(health_active_clients=bound),
    )
    srv = FederationServer()
    big = srv.create_session(
        "big_pop", big_cfg, _data(num_clients=64, feat=17),
        _model(feat=17),
    )
    small = srv.create_session(
        "small_pop", _cfg(comm_round=3, seed=3), _data(seed=3), _model(),
    )
    srv.start()
    results = srv.wait(timeout=180)
    assert results["big_pop"]["ok"] and results["small_pop"]["ok"]
    health = big.server.health
    # the bound came from PopulationConfig via from_config — one
    # definition for every runtime
    assert health._clients.capacity == bound
    assert len(health._clients) <= bound
    # the run genuinely exceeded the bound: spilled records exist and
    # total coverage (active + spilled) spans the participants
    assert health.known_client_count() > bound
    assert len(health._clients.spilled) > 0
    # spilled clients still answer with exact counters in the snapshot
    snap = health.snapshot()
    assert len(snap) == health.known_client_count()
    spilled_rows = [
        v for v in snap.values() if v["mean_train_s"] is None
    ]
    assert spilled_rows and all(
        r["rounds_participated"] >= 1 for r in spilled_rows
    )
    # the co-tenant's registry kept ITS default bound (per-tenant
    # isolation of the population knobs)
    assert small.server.health._clients.capacity == 65536
    srv.close()
