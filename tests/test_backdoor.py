"""Backdoor attack vs defense: the eval the reference runs with
FedAvgRobustAggregator.py:14-60 + edge_case_examples — round 1's gap was
that the defense was never shown defeating an attack (VERDICT #4)."""

import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.edge_cases import (
    PoisonSpec,
    apply_trigger,
    attack_success_rate,
    backdoor_test_set,
    poison_clients,
)
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.robustness import RobustConfig
from fedml_tpu.robustness.backdoor import AttackConfig, BackdoorFedAvgAPI

SPEC = PoisonSpec(target_label=0, poison_frac=0.5, trigger_size=3, trigger_value=2.5)


def _clean_data():
    return synthetic_classification(
        num_clients=8,
        num_classes=4,
        feat_shape=(10, 10, 1),
        samples_per_client=48,
        partition_method="homo",
        ragged=False,
        seed=7,
    )


def test_poison_clients_only_touches_attackers():
    data = _clean_data()
    poisoned = poison_clients(data, attacker_ids=[1, 5], spec=SPEC, seed=0)
    for c in range(data.num_clients):
        same = np.array_equal(poisoned.client_x[c], data.client_x[c])
        assert same == (c not in (1, 5))
    # poisoned samples carry the target label and the trigger patch
    changed = poisoned.client_x[1][..., :3, :3, :] != data.client_x[1][..., :3, :3, :]
    assert changed.any()
    n_target = int(np.sum(poisoned.client_y[1] == SPEC.target_label))
    assert n_target >= int(0.5 * len(poisoned.client_y[1]))


def test_backdoor_test_set_excludes_target_class():
    data = _clean_data()
    x, y = backdoor_test_set(data, SPEC)
    assert (y == SPEC.target_label).all()
    assert len(x) == int(np.sum(np.asarray(data.test_y) != SPEC.target_label))
    assert float(x[:, :3, :3].min()) == SPEC.trigger_value


def _run(defense: RobustConfig, rounds: int = 4):
    # Few rounds: norm clipping defends against model REPLACEMENT (the
    # boosted upload); a persistent poisoned-data attack trickles the
    # backdoor in "honestly" over many rounds regardless of clipping — at
    # 12 rounds both arms reach ASR 1.0 and the comparison is meaningless.
    data = poison_clients(_clean_data(), attacker_ids=[1, 5], spec=SPEC, seed=0)
    model = ModelDef(LogisticRegression(num_classes=4), (10, 10, 1), 4, name="lr")
    cfg = RunConfig(
        data=DataConfig(batch_size=16),
        fed=FedConfig(
            client_num_in_total=8,
            client_num_per_round=8,
            comm_round=rounds,
            epochs=1,
            frequency_of_the_test=10_000,
        ),
        train=TrainConfig(lr=0.1),
    )
    api = BackdoorFedAvgAPI(
        cfg,
        data,
        model,
        robust=defense,
        attack=AttackConfig(attacker_ids=(1, 5), boost=8.0),
    )
    for r in range(rounds):
        api.train_round(r)
    _, main_acc = api.evaluate_global()
    asr = attack_success_rate(model, api.global_vars, data, SPEC, eval_fn=api.eval_fn)
    return main_acc, asr


def test_defense_reduces_attack_success_rate():
    """The VERDICT #4 contract: ASR(defense) < ASR(no defense) at comparable
    main-task accuracy — the defense measurably defeats a boosted backdoor."""
    main_nodef, asr_nodef = _run(RobustConfig(defense_type="no_defense"))
    main_def, asr_def = _run(
        RobustConfig(defense_type="norm_diff_clipping", norm_bound=0.3)
    )
    # the boosted attack installs the backdoor without a defense
    assert asr_nodef > 0.5, f"attack too weak to test the defense (ASR={asr_nodef})"
    # clipping defeats it while keeping the main task working
    assert asr_def < 0.5 * asr_nodef, (asr_def, asr_nodef)
    assert main_def > 0.7, f"defense destroyed main-task accuracy ({main_def})"
    assert main_def >= main_nodef - 0.15
