"""Memory-mapped federated store (data/mmap_store.py): round math parity
with the in-RAM path, streaming write, and a 10k-client reduced-shape run
(VERDICT r2 Next #4 — the client-state store for clients >> RAM; ref
benchmark/README.md:57 federates 342,477 StackOverflow clients)."""

import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI, client_sampling
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.base import FederatedDataset, stack_clients
from fedml_tpu.data.mmap_store import (
    load_mmap_dataset,
    synth_stackoverflow_mmap,
    write_mmap_dataset,
)
from fedml_tpu.models import create_model


def _small_dataset(num_clients=16, seed=0):
    rng = np.random.default_rng(seed)
    sizes = rng.integers(4, 24, num_clients)
    cx = [rng.normal(size=(n, 6)).astype(np.float32) for n in sizes]
    cy = [rng.integers(0, 4, n).astype(np.int32) for n in sizes]
    tx = rng.normal(size=(32, 6)).astype(np.float32)
    ty = rng.integers(0, 4, 32).astype(np.int32)
    return FederatedDataset(
        name="ram", client_x=cx, client_y=cy, test_x=tx, test_y=ty,
        num_classes=4,
    )


def _as_mmap(data: FederatedDataset, path) -> object:
    flat_x = np.concatenate(list(data.client_x), axis=0)
    flat_y = np.concatenate(list(data.client_y), axis=0)
    sizes = data.train_sample_counts

    def gen_chunk(start, n):
        return flat_x[start:start + n], flat_y[start:start + n]

    write_mmap_dataset(
        str(path), sizes, gen_chunk, (data.test_x, data.test_y),
        num_classes=data.num_classes, name="mmapped", chunk_rows=37,
    )
    return load_mmap_dataset(str(path))


def test_mmap_round_batches_match_in_ram(tmp_path):
    ram = _small_dataset()
    mm = _as_mmap(ram, tmp_path / "store")
    assert mm.num_clients == ram.num_clients
    np.testing.assert_array_equal(
        mm.train_sample_counts, ram.train_sample_counts
    )
    sampled = client_sampling(3, ram.num_clients, 6)
    a = stack_clients(ram, sampled, 8, seed=42)
    b = stack_clients(mm, sampled, 8, seed=42)
    np.testing.assert_array_equal(a.x, b.x)
    np.testing.assert_array_equal(a.y, b.y)
    np.testing.assert_array_equal(a.mask, b.mask)
    np.testing.assert_array_equal(a.num_samples, b.num_samples)


def test_mmap_fedavg_rounds_match_in_ram(tmp_path):
    ram = _small_dataset()
    mm = _as_mmap(ram, tmp_path / "store")
    model = create_model("lr", "synthetic", (6,), 4)
    outs = {}
    for name, data in (("ram", ram), ("mmap", mm)):
        cfg = RunConfig(
            data=DataConfig(batch_size=8, device_cache=False),
            fed=FedConfig(
                client_num_in_total=data.num_clients, client_num_per_round=6,
                comm_round=3, epochs=1, frequency_of_the_test=10_000,
            ),
            train=TrainConfig(client_optimizer="sgd", lr=0.1),
            seed=0,
        )
        api = FedAvgAPI(cfg, data, model)
        for r in range(3):
            api.train_round(r)
        outs[name] = api.global_vars
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(outs["ram"]),
        jax.tree_util.tree_leaves(outs["mmap"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streaming_write_never_materializes(tmp_path):
    calls = []

    def gen_chunk(start, n):
        calls.append(n)
        r = np.random.default_rng(start)
        return (
            r.normal(size=(n, 3)).astype(np.float32),
            r.integers(0, 2, n).astype(np.int32),
        )

    sizes = [10] * 40  # 400 rows, chunk_rows=64 -> ceil(400/64)=7 chunks
    write_mmap_dataset(
        str(tmp_path / "s"), sizes, gen_chunk,
        (np.zeros((4, 3), np.float32), np.zeros(4, np.int32)),
        num_classes=2, chunk_rows=64,
    )
    assert max(calls) <= 64
    mm = load_mmap_dataset(str(tmp_path / "s"))
    assert mm.total_train_samples() == 400
    assert len(mm.client_x[3]) == 10


@pytest.mark.parametrize("num_clients", [10_000])
def test_10k_clients_reduced_shape(tmp_path, num_clients):
    """10k clients at tiny shapes through the full FedAvgAPI round path
    (CI-scale version of the 100k bench row)."""
    mm = synth_stackoverflow_mmap(
        str(tmp_path / "so"), num_clients=num_clients, mean_samples=8,
        vocab=64, seq_len=6, seed=1,
    )
    assert mm.num_clients == num_clients
    model = create_model("rnn", "stackoverflow", (6,), 64, vocab_size=64)
    cfg = RunConfig(
        data=DataConfig(batch_size=8, pad_bucket=4, device_cache=False),
        fed=FedConfig(
            client_num_in_total=num_clients, client_num_per_round=10,
            comm_round=2, epochs=1, frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    api = FedAvgAPI(cfg, mm, model, task="nwp")
    for r in range(2):
        _, m = api.train_round(r)
    assert np.isfinite(float(np.asarray(m["loss_sum"]).sum()))


def test_imagenet_streaming_store(tmp_path):
    """ImageNet streaming loader: metadata scan -> chunked decode into the
    mmap store; round batches match the in-RAM loader's math."""
    from fedml_tpu.data.imagenet import load_imagenet, load_imagenet_streaming

    rng = np.random.default_rng(0)
    root = tmp_path / "imgnet"
    for split, n in (("train", 6), ("val", 2)):
        for cname in ("n01440764", "n01443537"):
            d = root / split / cname
            d.mkdir(parents=True)
            for i in range(n):
                np.save(d / f"img_{i}.npy", rng.random((8, 8, 3)).astype(np.float32))
    stream = load_imagenet_streaming(
        str(root), str(tmp_path / "store"), num_clients=3, image_size=8,
        chunk_rows=5, seed=0,
    )
    ram = load_imagenet(str(root), num_clients=3, image_size=8, seed=0)
    assert stream.num_clients == 3
    # identical partition (same seed/partitioner): shards must match
    for i in range(3):
        np.testing.assert_allclose(
            np.asarray(stream.client_x[i]), ram.client_x[i], atol=1e-6
        )
        np.testing.assert_array_equal(
            np.asarray(stream.client_y[i]), ram.client_y[i]
        )
    # idempotent reload
    again = load_imagenet_streaming(
        str(root), str(tmp_path / "store"), num_clients=3, image_size=8,
    )
    assert again.total_train_samples() == stream.total_train_samples()


# ---------------------------------------------------------------------------
# incremental builder (MmapStoreBuilder): bounded RAM, header rewrite
# ---------------------------------------------------------------------------


def test_builder_bitmatches_bulk_writer(tmp_path):
    """Clients streamed one at a time through the builder produce a store
    byte-identical to the bulk writer's — same files, same loader."""
    from fedml_tpu.data.mmap_store import MmapStoreBuilder

    data = _small_dataset()
    bulk = _as_mmap(data, tmp_path / "bulk")
    b = MmapStoreBuilder(str(tmp_path / "inc"), flush_bytes=1 << 10)
    for x, y in zip(data.client_x, data.client_y):
        b.add_client(x, y)
    b.finalize((data.test_x, data.test_y), num_classes=4, name="mmapped")
    inc = load_mmap_dataset(str(tmp_path / "inc"))
    assert inc.num_clients == bulk.num_clients
    for i in range(inc.num_clients):
        np.testing.assert_array_equal(
            np.asarray(inc.client_x[i]), np.asarray(bulk.client_x[i])
        )
        np.testing.assert_array_equal(
            np.asarray(inc.client_y[i]), np.asarray(bulk.client_y[i])
        )
    np.testing.assert_array_equal(inc.test_x, bulk.test_x)


def test_builder_ram_ceiling_and_stats(tmp_path):
    """The buffer never holds more than flush_bytes + one client; stats
    expose the mmap_build/* summary row with real flush counts."""
    from fedml_tpu.data.mmap_store import MmapStoreBuilder

    rng = np.random.default_rng(0)
    ceiling = 4 << 10
    logs = []
    b = MmapStoreBuilder(
        str(tmp_path / "s"), flush_bytes=ceiling, log_fn=logs.append
    )
    client_bytes = []
    for _ in range(64):
        n = int(rng.integers(4, 12))
        x = rng.normal(size=(n, 6)).astype(np.float32)
        y = rng.integers(0, 4, n).astype(np.int32)
        client_bytes.append(x.nbytes + y.nbytes)
        b.add_client(x, y)
    b.finalize(
        (np.zeros((4, 6), np.float32), np.zeros(4, np.int32)), num_classes=4
    )
    stats = b.stats()
    assert stats["mmap_build/clients"] == 64
    assert stats["mmap_build/flushes"] >= 2
    assert stats["mmap_build/peak_buffer_bytes"] <= ceiling + max(client_bytes)
    assert stats["mmap_build/rows"] == load_mmap_dataset(
        str(tmp_path / "s")
    ).total_train_samples()
    assert stats["mmap_build/bytes"] > 0 and stats["mmap_build/seconds"] >= 0
    # progress strings while flushing + the final stats row
    assert any(isinstance(m, str) and "mmap build" in m for m in logs)
    assert any(isinstance(m, dict) and "mmap_build/rows" in m for m in logs)


def test_builder_rejects_drift_and_reuse(tmp_path):
    from fedml_tpu.data.mmap_store import MmapStoreBuilder

    b = MmapStoreBuilder(str(tmp_path / "s"))
    b.add_client(np.zeros((3, 6), np.float32), np.zeros(3, np.int32))
    with pytest.raises(ValueError, match="drift"):
        b.add_client(np.zeros((3, 5), np.float32), np.zeros(3, np.int32))
    with pytest.raises(ValueError, match="misaligned"):
        b.add_client(np.zeros((3, 6), np.float32), np.zeros(2, np.int32))
    b.finalize((np.zeros((2, 6), np.float32), np.zeros(2, np.int32)), 4)
    with pytest.raises(RuntimeError, match="finalized"):
        b.add_client(np.zeros((3, 6), np.float32), np.zeros(3, np.int32))


def test_builder_store_trains_identically_to_ram(tmp_path):
    """End-to-end: a builder-written store drives the same FedAvg rounds
    as the in-RAM dataset (the loader-parity contract real-format
    loaders rely on)."""
    from fedml_tpu.data.mmap_store import MmapStoreBuilder

    data = _small_dataset()
    b = MmapStoreBuilder(str(tmp_path / "inc"), flush_bytes=1 << 10)
    for x, y in zip(data.client_x, data.client_y):
        b.add_client(x, y)
    b.finalize((data.test_x, data.test_y), num_classes=4, name="ram")
    mm = load_mmap_dataset(str(tmp_path / "inc"))
    cfg = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=16, client_num_per_round=4, comm_round=3,
            epochs=1, frequency_of_the_test=100,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    model = create_model("lr", "synthetic", (6,), 4)
    ram_api = FedAvgAPI(cfg, data, model)
    ram_api.train()
    mm_api = FedAvgAPI(cfg, mm, model)
    mm_api.train()
    for ra, rb in zip(ram_api.history, mm_api.history):
        assert ra["Train/Loss"] == rb["Train/Loss"]
