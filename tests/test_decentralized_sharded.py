"""Mesh-sharded gossip (ppermute bands) == dense-einsum simulator."""

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.decentralized import make_decentralized_run
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.parallel.decentralized_sharded import (
    cyclic_decompose,
    make_sharded_decentralized_run,
)
from fedml_tpu.parallel.mesh import make_mesh
from fedml_tpu.partition.topology import SymmetricTopologyManager


def test_cyclic_decompose_reconstructs_W():
    topo = SymmetricTopologyManager(8, neighbor_num=2)
    topo.generate_topology()
    W = np.asarray(topo.topology, np.float32)
    offsets, weights = cyclic_decompose(W)
    N = W.shape[0]
    R = np.zeros_like(W)
    idx = np.arange(N)
    for k, d in enumerate(offsets):
        R[idx, (idx + d) % N] += weights[:, k]
    np.testing.assert_allclose(R, W, atol=1e-7)
    # ring + sparse random links realize far fewer than N bands
    assert len(offsets) < N


@pytest.mark.parametrize("variant", ["dsgd", "pushsum"])
def test_sharded_gossip_matches_dense(variant):
    N, T, D = 8, 12, 6
    topo = SymmetricTopologyManager(N, neighbor_num=2)
    topo.generate_topology()
    model = ModelDef(
        LogisticRegression(num_classes=1), input_shape=(D,), num_classes=1,
        name="lr",
    )
    keys = jax.random.split(jax.random.PRNGKey(0), N)
    params = jax.vmap(lambda k: model.init(k)["params"])(keys)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, T, D)).astype(np.float32)
    y = (rng.random(size=(N, T)) > 0.5).astype(np.float32)

    dense = make_decentralized_run(model, topo.topology, lr=0.1, variant=variant)
    p_dense, l_dense = dense(params, x, y)

    mesh = make_mesh(N, axis_name="workers")
    sharded = make_sharded_decentralized_run(
        model, topo.topology, mesh, lr=0.1, variant=variant
    )
    p_shard, l_shard = sharded(params, x, y)

    np.testing.assert_allclose(
        np.asarray(l_dense), np.asarray(l_shard), rtol=1e-5, atol=1e-6
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(p_dense), jax.tree_util.tree_leaves(p_shard)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


@pytest.mark.parametrize("variant", ["dsgd", "pushsum"])
def test_sharded_gossip_matches_dense_asymmetric(variant):
    """Directed (asymmetric, non-circulant) topology: here W != Wᵀ and the
    band weights differ per worker, so this exercises the pushsum
    transpose branch and the ppermute direction for real (the symmetric
    ring's uniform circulant W would mask a sign error in either)."""
    from fedml_tpu.partition.topology import AsymmetricTopologyManager

    N, T, D = 8, 10, 5
    topo = AsymmetricTopologyManager(N, undirected_neighbor_num=3, seed=7)
    topo.generate_topology()
    W = np.asarray(topo.topology)
    assert not np.allclose(W, W.T)  # genuinely directed
    model = ModelDef(
        LogisticRegression(num_classes=1), input_shape=(D,), num_classes=1,
    )
    keys = jax.random.split(jax.random.PRNGKey(2), N)
    params = jax.vmap(lambda k: model.init(k)["params"])(keys)
    rng = np.random.default_rng(3)
    x = rng.normal(size=(N, T, D)).astype(np.float32)
    y = (rng.random(size=(N, T)) > 0.5).astype(np.float32)

    dense = make_decentralized_run(model, W, lr=0.1, variant=variant)
    p_dense, l_dense = dense(params, x, y)
    sharded = make_sharded_decentralized_run(
        model, W, make_mesh(N, axis_name="workers"), lr=0.1, variant=variant
    )
    p_shard, l_shard = sharded(params, x, y)
    np.testing.assert_allclose(
        np.asarray(l_dense), np.asarray(l_shard), rtol=1e-5, atol=1e-6
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(p_dense), jax.tree_util.tree_leaves(p_shard)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_variant_validated():
    topo = SymmetricTopologyManager(8, neighbor_num=2)
    topo.generate_topology()
    model = ModelDef(
        LogisticRegression(num_classes=1), input_shape=(4,), num_classes=1,
    )
    with pytest.raises(ValueError, match="dsgd.*pushsum"):
        make_decentralized_run(model, topo.topology, lr=0.1, variant="push-sum")
    with pytest.raises(ValueError, match="dsgd.*pushsum"):
        make_sharded_decentralized_run(
            model, topo.topology, make_mesh(8, axis_name="w"), lr=0.1,
            variant="push_sum",
        )


def test_sharded_gossip_requires_matching_mesh():
    topo = SymmetricTopologyManager(8, neighbor_num=2)
    topo.generate_topology()
    model = ModelDef(
        LogisticRegression(num_classes=1), input_shape=(4,), num_classes=1,
    )
    mesh = make_mesh(4, axis_name="workers")
    with pytest.raises(ValueError, match="one gossip worker per shard"):
        make_sharded_decentralized_run(model, topo.topology, mesh, lr=0.1)
