"""Split & vertical federation (fedml_tpu/splitfed/, PR 19).

The load-bearing contracts:

- **sim-vs-transport parity** — the boundary-cut message protocol
  (forward → acts → server step → grads → backward) over the loopback
  wire produces BYTE-identical params to the fused ``SplitNNAPI``
  simulator over the same scheduler-selected cohorts. VFL parity is
  allclose, not byte: XLA fuses across the party-sum in the fused step,
  reordering the flop sequence (~1e-8) — pinned here so a regression to
  worse than 1e-6 still fails.
- **opt-state partition** — merge/split between the fused optimizer tree
  (what checkpoints carry) and the per-group wire states is an exact
  inverse pair.
- **warm-vs-cold** — AOT warmup changes when programs compile, never
  what they compute.
- **fault-injected relay** — a crashed client's turn is declined
  explicitly (no quorum deadline exists to absorb silence); recovery is
  deterministic: two identical faulted runs agree byte-for-byte.
- **supervised restart** — a split tenant killed mid-flight self-heals
  from its rolling checkpoint with bit parity (both param groups + the
  fused opt state round-trip).
- **activation-wire compression** — the int8/int4 cut factor is read
  off the comm meter (on_uplink/on_downlink), never asserted from the
  codec's spec sheet.
"""

import os

import jax
import numpy as np
import pytest

from fedml_tpu.config import (
    CommConfig,
    DataConfig,
    FedConfig,
    RunConfig,
    TrainConfig,
)
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.telemetry import get_comm_meter


def _cfg(comm_round=2, workers=3, total=5, seed=11, comm=None, **fed_kw):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=total, client_num_per_round=workers,
            comm_round=comm_round, epochs=1, frequency_of_the_test=100,
            **fed_kw,
        ),
        train=TrainConfig(
            client_optimizer="sgd", lr=0.1, momentum=0.9, wd=5e-4
        ),
        comm=comm or CommConfig(),
        seed=seed,
    )


def _data(num_clients=5, seed=0):
    return synthetic_classification(
        num_clients=num_clients, num_classes=3, feat_shape=(10,),
        samples_per_client=24, partition_method="homo", seed=seed,
    )


def _tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _cohorts(cfg, data):
    """The ring orders the transport's server will draw — derived from an
    IDENTICAL scheduler (same config/seed/policy), which is the parity
    contract: ring order comes from the SelectionPolicy registry, not a
    hardcoded neighbor list."""
    from fedml_tpu.scheduler import ClientScheduler

    sched = ClientScheduler.from_config(
        cfg, num_clients=cfg.fed.client_num_in_total, data=data
    )
    return [
        list(sched.select(r, k=cfg.fed.client_num_per_round))
        for r in range(cfg.fed.comm_round)
    ]


# ---------------------------------------------------------------------------
# boundary programs: composition == fused step, opt-state partition
# ---------------------------------------------------------------------------


def test_boundary_composition_matches_fused_step_bitwise():
    """client_forward → server_step → client_backward over per-group opt
    states == the fused step over the joint param dict, byte-for-byte,
    including a numpy wire round-trip of the activations/grads."""
    from fedml_tpu.algorithms.split_nn import default_split_models
    from fedml_tpu.splitfed.programs import (
        make_split_optimizer,
        make_splitnn_client_backward,
        make_splitnn_client_forward,
        make_splitnn_fused_step,
        make_splitnn_server_step,
        merge_opt_state,
        split_opt_state,
    )

    bottom, top = default_split_models((10,), 3)
    lr, mom, wd = 0.1, 0.9, 5e-4
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    bp = jax.device_get(bottom.init(k1))["params"]
    tp = jax.device_get(top.init(k2))["params"]
    opt = make_split_optimizer(lr, mom, wd)
    fused = make_splitnn_fused_step(bottom, top, lr=lr, momentum=mom, wd=wd)
    fwd = make_splitnn_client_forward(bottom)
    srv = make_splitnn_server_step(top, lr, mom, wd)
    bwd = make_splitnn_client_backward(bottom, lr, mom, wd)

    params = {"bottom": bp, "top": tp}
    fused_state = opt.init(params)
    b_state, t_state = split_opt_state(opt, fused_state, bp, tp)

    rng = np.random.default_rng(3)
    for step in range(4):
        x = rng.standard_normal((8, 10)).astype(np.float32)
        y = rng.integers(0, 3, size=(8,)).astype(np.int32)
        params, fused_state, loss_f, _ = fused(params, fused_state, x, y)
        # the wire composition: acts and grads cross as numpy
        acts = np.asarray(fwd(bp, x))
        tp, t_state, loss_b, _, acts_grad = srv(tp, t_state, acts, y)
        bp, b_state = bwd(bp, b_state, x, np.asarray(acts_grad))
        np.testing.assert_array_equal(
            np.asarray(loss_f), np.asarray(loss_b)
        )
        _tree_equal(params["bottom"], bp)
        _tree_equal(params["top"], tp)
    # and the state partition is an exact inverse pair
    merged = merge_opt_state(opt, b_state, t_state, bp, tp)
    _tree_equal(fused_state, merged)
    b2, t2 = split_opt_state(opt, merged, bp, tp)
    _tree_equal(b_state, b2)
    _tree_equal(t_state, t2)


def test_vfl_party_opt_state_partition_roundtrips():
    import optax

    from fedml_tpu.splitfed.programs import (
        merge_party_opt_states,
        split_party_opt_states,
    )
    from fedml_tpu.algorithms.vertical_fl import VFLParty

    rngs = jax.random.split(jax.random.PRNGKey(5), 3)
    parties = [
        VFLParty(d, 16, 1, rngs[i], has_labels=(i == 0))
        for i, d in enumerate((4, 3, 3))
    ]
    all_params = [p.params for p in parties]
    opt = optax.sgd(0.05, momentum=0.9)
    fused = opt.init(all_params)
    states = split_party_opt_states(opt, fused, all_params)
    assert len(states) == 3
    _tree_equal(fused, merge_party_opt_states(opt, states, all_params))


def test_default_split_models_derives_cut_width_by_eval_shape():
    """The top half's input width must equal whatever the bottom actually
    emits — for conv bottoms that is stride arithmetic the old hardcoded
    ``(d+3)//4`` got wrong for non-multiple-of-4 inputs."""
    import jax.numpy as jnp

    from fedml_tpu.algorithms.split_nn import default_split_models

    for shape in ((10,), (8, 8, 1), (9, 9, 2), (11, 7, 3)):
        bottom, top = default_split_models(shape, 3)
        v = bottom.init(jax.random.PRNGKey(0))
        acts, _ = bottom.apply(
            v, jnp.zeros((2,) + shape, jnp.float32), train=False
        )
        assert top.input_shape == (int(acts.shape[-1]),), shape
        # the composition must actually run
        tv = top.init(jax.random.PRNGKey(1))
        logits, _ = top.apply(tv, acts, train=False)
        assert logits.shape == (2, 3)


# ---------------------------------------------------------------------------
# sim-vs-transport parity
# ---------------------------------------------------------------------------


def test_splitnn_transport_matches_fused_simulator_bitwise():
    from fedml_tpu.algorithms.split_nn import SplitNNAPI, default_split_models
    from fedml_tpu.splitfed import run_loopback_splitnn

    cfg = _cfg(comm_round=2, workers=3)
    data = _data()
    server = run_loopback_splitnn(cfg, data)
    assert server.round_idx == 2
    assert server.skipped_turns == 0

    bottom, top = default_split_models(
        tuple(data.client_x[0].shape[1:]), data.num_classes
    )
    api = SplitNNAPI(
        bottom, top, lr=cfg.train.lr, momentum=cfg.train.momentum,
        wd=cfg.train.wd, seed=cfg.seed,
    )
    for cohort in _cohorts(cfg, data):
        api.train_ring(
            [(data.client_x[c], data.client_y[c]) for c in cohort],
            batch_size=cfg.data.batch_size,
            epochs_per_client=cfg.fed.epochs,
        )
    _tree_equal(
        server.global_vars["params"]["bottom"], api.bottom_vars["params"]
    )
    _tree_equal(server.global_vars["params"]["top"], api.top_vars["params"])


def test_vfl_transport_matches_fused_simulator():
    """Guest + 2 hosts over the wire vs VFLAPI.train_epoch. NOT byte-
    exact by design: the fused step lets XLA fuse across the party sum,
    reordering flops — the bound pins the divergence to float32 noise."""
    from fedml_tpu.algorithms.vertical_fl import VFLAPI
    from fedml_tpu.splitfed import run_loopback_vfl

    cfg = _cfg(comm_round=2, workers=2, seed=4)
    rng = np.random.default_rng(9)
    n, splits = 48, (4, 3, 3)
    xs = [rng.standard_normal((n, d)).astype(np.float32) for d in splits]
    y = (rng.integers(0, 2, size=(n,))).astype(np.float32)

    guest, hosts = run_loopback_vfl(cfg, xs, y)
    api = VFLAPI(feature_splits=list(splits), lr=cfg.train.lr, seed=cfg.seed)
    for _ in range(cfg.fed.comm_round):
        api.train_epoch(xs, y, batch_size=cfg.data.batch_size)

    def close(a, b):
        for x_, y_ in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            np.testing.assert_allclose(
                np.asarray(x_), np.asarray(y_), atol=1e-6, rtol=1e-5
            )

    close(guest.params, api.params[0])
    for h, pp in zip(hosts, api.params[1:]):
        close(h.params, pp)
    assert len(guest.history) == cfg.fed.comm_round
    assert "Train/Loss" in guest.history[-1]


# ---------------------------------------------------------------------------
# warm vs cold
# ---------------------------------------------------------------------------


def test_split_warmup_is_numerically_invisible():
    """warmup_splitnn AOT-compiles the five split programs; a warmed
    session's result is byte-identical to a cold one, and the compile
    telemetry rows land in the log stream."""
    from fedml_tpu.serve import FedSession

    cfg, data = _cfg(), _data()
    cold = FedSession(cfg, data, None, algorithm="split_nn").run()
    rows = []
    warm = FedSession(
        cfg, data, None, algorithm="split_nn", warmup=True,
        log_fn=rows.append,
    ).run()
    _tree_equal(cold.global_vars, warm.global_vars)
    crow = [r for r in rows if "compile/warmup_s" in r]
    assert crow, "warmup emitted no compile row"
    for prog in ("split_forward", "split_server_step", "split_backward",
                 "split_fused", "split_eval"):
        assert any(
            k.startswith(f"compile/{prog}") for k in crow[0]
        ), (prog, sorted(crow[0]))


# ---------------------------------------------------------------------------
# faults: explicit decline + deterministic recovery
# ---------------------------------------------------------------------------


def _faulted(cfg, data, plan_json):
    from fedml_tpu.scheduler import FaultInjector, FaultPlan
    from fedml_tpu.splitfed import run_loopback_splitnn

    inj = FaultInjector(FaultPlan.from_json(plan_json))
    rows = []
    server = run_loopback_splitnn(
        cfg, data, log_fn=rows.append, faults=inj
    )
    return server, rows


def test_faulted_boundary_round_recovers_deterministically():
    """A client crashed from round 0 declines every turn: the server
    relays the unchanged bottom state past it, the round completes, the
    skip is visible in the round row — and the whole faulted run is
    bit-reproducible."""
    plan = {"clients": {"1": {"crash_at_round": 0}}}
    cfg, data = _cfg(comm_round=2, workers=3), _data()

    a, rows_a = _faulted(cfg, data, plan)
    b, _rows_b = _faulted(cfg, data, plan)
    assert a.round_idx == 2
    assert a.skipped_turns > 0
    done = [r for r in rows_a if "t_s" in r and "round" in r]
    assert done and all("split/skipped_turns" in r for r in done)
    assert done[-1]["split/skipped_turns"] == a.skipped_turns
    _tree_equal(a.global_vars, b.global_vars)
    _tree_equal(a._server_opt_state, b._server_opt_state)

    # the crashed client contributed nothing: the run equals a clean run
    # where that client's turns never update the relay — i.e. it still
    # DIFFERS from the no-fault run (the decline is not a silent no-op)
    clean = _faulted(cfg, data, {})[0]
    leaves_a = jax.tree_util.tree_leaves(a.global_vars)
    leaves_c = jax.tree_util.tree_leaves(clean.global_vars)
    assert any(
        not np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(leaves_a, leaves_c)
    )


def test_flaky_duplicate_done_is_deduped():
    """flaky_p=1 double-sends every DONE; the server's (round, worker)
    dedupe absorbs the duplicates — same result as the clean run."""
    plan = {"default": {"flaky_upload_p": 1.0}}
    cfg, data = _cfg(comm_round=2, workers=2), _data()
    flaky, _ = _faulted(cfg, data, plan)
    clean, _ = _faulted(cfg, data, {})
    assert flaky.dropped_boundary > 0  # duplicates arrived and were dropped
    _tree_equal(flaky.global_vars, clean.global_vars)


# ---------------------------------------------------------------------------
# serve integration: co-residency, checkpoint, supervised restart
# ---------------------------------------------------------------------------


def test_split_tenant_checkpoint_resume_bit_parity(tmp_path):
    from fedml_tpu.serve import FedSession

    data = _data()
    ck = str(tmp_path / "split.ckpt")
    FedSession(
        _cfg(comm_round=2), data, None, algorithm="split_nn",
        checkpoint_path=ck, checkpoint_every=1,
    ).run()
    assert os.path.exists(ck + ".npz")
    resumed = FedSession(
        _cfg(comm_round=4), data, None, algorithm="split_nn",
        checkpoint_path=ck, checkpoint_every=1, resume=True,
    ).run()
    ref = FedSession(_cfg(comm_round=4), data, None,
                     algorithm="split_nn").run()
    assert resumed.round_idx == 4
    _tree_equal(resumed.global_vars, ref.global_vars)
    _tree_equal(resumed._server_opt_state, ref._server_opt_state)


def test_split_tenant_supervised_restart_bit_parity(tmp_path):
    """The soak_d twin for split federations: kill the tenant mid-flight
    via a poisoned log row; the supervisor restarts it from the rolling
    checkpoint; final params (both groups) match an uninterrupted run."""
    from fedml_tpu.serve import FedSession, RestartPolicy, SupervisedSession

    data = _data()
    ref = FedSession(_cfg(comm_round=4), data, None,
                     algorithm="split_nn").run()
    state = {"killed": False}

    def chaos(row):
        if row.get("round") == 1 and "t_s" in row and not state["killed"]:
            state["killed"] = True
            raise RuntimeError("chaos kill")

    sup = SupervisedSession(
        _cfg(comm_round=4), data, None, algorithm="split_nn",
        name="heal_split",
        restart=RestartPolicy(budget=2, backoff_base_s=0.02),
        checkpoint_path=str(tmp_path / "heal.ckpt"), checkpoint_every=1,
        log_fn=chaos,
    )
    healed = sup.run()
    assert sup.restarts == 1
    _tree_equal(ref.global_vars, healed.global_vars)


def test_split_tenant_coresident_with_horizontal_tenant():
    """One FedSession host, two tenants: a horizontal fedavg federation
    and a split federation run concurrently in one process; both finish
    and neither perturbs the other (the split run matches its solo
    twin)."""
    from fedml_tpu.models import create_model
    from fedml_tpu.serve import FedSession

    data = _data()
    solo = FedSession(_cfg(), data, None, algorithm="split_nn").run()

    model = create_model("lr", "synthetic", (10,), 3)
    horiz = FedSession(
        _cfg(), data, model, algorithm="fedavg", name="horiz",
    ).start()
    split = FedSession(
        _cfg(), data, None, algorithm="split_nn", name="split",
    ).start()
    hsrv = horiz.wait(timeout=120)
    ssrv = split.wait(timeout=120)
    assert hsrv.round_idx == 2 and ssrv.round_idx == 2
    _tree_equal(solo.global_vars, ssrv.global_vars)


# ---------------------------------------------------------------------------
# activation-wire compression: the cut factor off comm/*
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("method,min_cut", [("int8", 3.0), ("int4", 5.0)])
def test_activation_compression_cut_factor_metered(method, min_cut):
    from fedml_tpu.splitfed import run_loopback_splitnn

    data = _data()
    cfg = _cfg(
        comm_round=1, workers=2,
        comm=CommConfig(
            activation_compression=method, activation_error_feedback=True
        ),
    )
    meter = get_comm_meter()
    before = meter.snapshot()
    server = run_loopback_splitnn(cfg, data)
    after = meter.snapshot()
    assert server.round_idx == 1
    up_p = after["uplink_payload_bytes"] - before["uplink_payload_bytes"]
    up_r = after["uplink_raw_bytes"] - before["uplink_raw_bytes"]
    dn_p = after["downlink_payload_bytes"] - before["downlink_payload_bytes"]
    dn_r = after["downlink_raw_bytes"] - before["downlink_raw_bytes"]
    assert up_r > 0 and dn_r > 0
    assert up_r / up_p >= min_cut, (method, up_p, up_r)
    assert dn_r / dn_p >= min_cut, (method, dn_p, dn_r)


def test_compressed_split_run_stays_close_to_exact():
    """int8 boundary quantization with error feedback: lossy but sane —
    the final params stay within quantization noise of the exact run,
    and the run completes every round."""
    from fedml_tpu.splitfed import run_loopback_splitnn

    data = _data()
    exact = run_loopback_splitnn(_cfg(comm_round=2, workers=2), data)
    lossy = run_loopback_splitnn(
        _cfg(
            comm_round=2, workers=2,
            comm=CommConfig(
                activation_compression="int8",
                activation_error_feedback=True,
            ),
        ),
        data,
    )
    assert lossy.round_idx == 2
    for a, b in zip(
        jax.tree_util.tree_leaves(exact.global_vars),
        jax.tree_util.tree_leaves(lossy.global_vars),
    ):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(1.0, float(np.max(np.abs(a))))
        assert float(np.max(np.abs(a - b))) / scale < 0.15


def test_activation_codec_rejects_unknown_method():
    from fedml_tpu.splitfed import ActivationCodec, run_loopback_splitnn

    with pytest.raises(ValueError):
        ActivationCodec("topk")
    with pytest.raises(ValueError):
        run_loopback_splitnn(
            _cfg(comm=CommConfig(activation_compression="zstd")), _data()
        )
