"""Transport retry layer (core/retry.py + the BaseCommManager send
template): deterministic backoff/chaos streams, retry/give-up accounting,
and the flaky-transport federation contract — injected send failures
survive with retries > 0, gave_up == 0, and numerics identical to a
fault-free run (the ci.sh chaos gate)."""

import jax
import numpy as np
import pytest

from fedml_tpu.config import (
    CommConfig,
    DataConfig,
    FedConfig,
    RunConfig,
    TrainConfig,
)
from fedml_tpu.core.comm import BaseCommManager
from fedml_tpu.core.message import Message, MessageType as MT
from fedml_tpu.core.retry import InjectedSendFault, RetryPolicy
from fedml_tpu.telemetry import TelemetryScope


class _FlakyComm(BaseCommManager):
    """A backend whose _send fails the first ``fail_first`` attempts of
    every message."""

    def __init__(self, fail_first=0):
        super().__init__()
        self.fail_first = fail_first
        self.attempts = 0
        self.delivered = []

    def _send(self, msg):
        self.attempts += 1
        if self.attempts <= self.fail_first:
            raise ConnectionError(f"transient #{self.attempts}")
        self.delivered.append(msg)

    def handle_receive_message(self):  # pragma: no cover - unused
        pass

    def stop_receive_message(self):  # pragma: no cover - unused
        pass


def _msg():
    return Message(MT.C2S_SEND_STATS, 1, 0)


def _fast(**kw):
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("backoff_max_s", 0.002)
    return RetryPolicy(**kw)


# ---------------------------------------------------------------------------
# policy determinism
# ---------------------------------------------------------------------------


def test_from_config_none_when_off():
    assert RetryPolicy.from_config(CommConfig()) is None
    p = RetryPolicy.from_config(CommConfig(send_retries=3), seed=9)
    assert p.max_attempts == 4 and p.seed == 9
    # chaos without retries still builds a policy (the CLI guards the
    # combination; programmatic callers get the give-up accounting)
    assert RetryPolicy.from_config(CommConfig(send_fault_p=0.5)) is not None


def test_backoff_is_deterministic_jittered_and_capped():
    p = RetryPolicy(max_attempts=5, backoff_base_s=0.1, backoff_max_s=0.5, seed=3)
    seq = [p.backoff_s(0, a) for a in range(1, 6)]
    assert seq == [p.backoff_s(0, a) for a in range(1, 6)]  # pure
    # jitter stays within [0.5, 1.5) of the exponential raw value, capped
    for a, s in enumerate(seq, start=1):
        raw = 0.1 * 2 ** (a - 1)
        assert min(0.5, 0.5 * raw) <= s <= min(0.5, 1.5 * raw)
    assert max(seq) <= 0.5  # capped
    # a different seed moves the jitter
    q = RetryPolicy(max_attempts=5, backoff_base_s=0.1, backoff_max_s=0.5, seed=4)
    assert [q.backoff_s(0, a) for a in range(1, 6)] != seq


def test_chaos_injection_is_pure_in_seed_seq_attempt():
    p = _fast(max_attempts=2, fault_p=0.5, seed=11)
    grid = [(s, a) for s in range(64) for a in range(2)]
    flips = [p.injects(s, a) for s, a in grid]
    assert flips == [p.injects(s, a) for s, a in grid]
    assert any(flips) and not all(flips)  # a real coin, not a constant
    q = _fast(max_attempts=2, fault_p=0.5, seed=12)
    assert [q.injects(s, a) for s, a in grid] != flips


# ---------------------------------------------------------------------------
# the send template
# ---------------------------------------------------------------------------


def test_no_policy_is_legacy_single_attempt():
    comm = _FlakyComm(fail_first=1)
    with pytest.raises(ConnectionError):
        comm.send_message(_msg())
    assert comm.attempts == 1 and not comm.delivered


def test_retry_delivers_after_transient_failures():
    scope = TelemetryScope(tenant="t")
    with scope.activate():
        comm = _FlakyComm(fail_first=2)
    comm.set_retry_policy(_fast(max_attempts=4))
    comm.send_message(_msg())
    assert comm.attempts == 3 and len(comm.delivered) == 1
    snap = scope.comm_meter.snapshot()
    assert sum(snap["send_retries"].values()) == 2
    assert sum(snap["send_gave_up"].values()) == 0
    # the delivered message IS counted as sent
    assert sum(snap["messages_sent"].values()) == 1


def test_retry_gives_up_after_attempt_cap_and_raises_original():
    scope = TelemetryScope(tenant="t")
    with scope.activate():
        comm = _FlakyComm(fail_first=100)
    comm.set_retry_policy(_fast(max_attempts=3))
    with pytest.raises(ConnectionError):
        comm.send_message(_msg())
    assert comm.attempts == 3
    snap = scope.comm_meter.snapshot()
    assert sum(snap["send_retries"].values()) == 2
    assert sum(snap["send_gave_up"].values()) == 1
    assert sum(snap["messages_sent"].values()) == 0  # never counted as sent


def test_retry_deadline_caps_total_time():
    scope = TelemetryScope(tenant="t")
    with scope.activate():
        comm = _FlakyComm(fail_first=100)
    # huge attempt budget but a deadline the second backoff would cross
    comm.set_retry_policy(RetryPolicy(
        max_attempts=1000, backoff_base_s=0.2, backoff_max_s=0.2,
        deadline_s=0.05,
    ))
    with pytest.raises(ConnectionError):
        comm.send_message(_msg())
    assert comm.attempts < 5  # gave up on the deadline, not the cap


def test_injected_faults_are_retried_and_deterministic():
    poly = _fast(max_attempts=8, fault_p=0.5, seed=5)

    def run():
        scope = TelemetryScope(tenant="t")
        with scope.activate():
            comm = _FlakyComm(fail_first=0)
        comm.set_retry_policy(poly)
        for _ in range(20):
            comm.send_message(_msg())
        snap = scope.comm_meter.snapshot()
        return (
            len(comm.delivered),
            sum(snap["send_retries"].values()),
            sum(snap["send_gave_up"].values()),
        )

    first = run()
    assert first[0] == 20 and first[1] > 0 and first[2] == 0
    assert run() == first  # the chaos schedule replays identically


def test_injected_fault_without_retries_gives_up():
    comm = _FlakyComm(fail_first=0)
    comm.set_retry_policy(RetryPolicy(max_attempts=1, fault_p=1.0))
    with pytest.raises(InjectedSendFault):
        comm.send_message(_msg())
    assert not comm.delivered  # the chaos fault fires BEFORE the wire


# ---------------------------------------------------------------------------
# grpc satellite: configurable timeout, retry-owned reconnects
# ---------------------------------------------------------------------------


def test_grpc_send_timeout_is_config_not_hardcoded():
    pytest.importorskip("grpc")
    from fedml_tpu.core.grpc_comm import GrpcCommManager

    comm = GrpcCommManager(
        0, {0: "127.0.0.1"}, base_port=18990, send_timeout_s=3.5
    )
    try:
        assert comm.send_timeout_s == 3.5
        assert comm.handshake_timeout_s == 120.0
    finally:
        comm.stop_receive_message()


def test_grpc_retry_policy_owns_reconnects_no_handshake_stall():
    """With a retry policy installed, a send to a dead peer fails fast at
    send_timeout_s per attempt (no one-shot 120 s wait_for_ready) and the
    template retries it — here to exhaustion, quickly."""
    pytest.importorskip("grpc")
    import time

    from fedml_tpu.core.grpc_comm import GrpcCommManager

    comm = GrpcCommManager(
        1, {1: "127.0.0.1", 0: "127.0.0.1"}, base_port=18992,
        send_timeout_s=0.2,
    )
    comm.set_retry_policy(_fast(max_attempts=2))
    try:
        t0 = time.monotonic()
        with pytest.raises(Exception):
            comm.send_message(Message(MT.C2S_SEND_STATS, 1, 0))  # rank 0 dead
        assert time.monotonic() - t0 < 10.0  # not the 120 s handshake
    finally:
        comm.stop_receive_message()


# ---------------------------------------------------------------------------
# federation contract: flaky transport, unchanged numerics (acceptance c)
# ---------------------------------------------------------------------------


def _data_model():
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model

    data = synthetic_classification(
        num_clients=6, num_classes=3, feat_shape=(10,),
        samples_per_client=24, partition_method="homo", seed=0,
    )
    return data, create_model("lr", "synthetic", (10,), 3)


def _cfg(**comm_kw):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3, comm_round=3,
            epochs=1, frequency_of_the_test=100,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        comm=CommConfig(**comm_kw),
        seed=0,
    )


def test_flaky_transport_federation_matches_fault_free():
    from fedml_tpu.serve import FedSession

    data, model = _data_model()
    clean = FedSession(
        _cfg(), data, model, name="rt_clean",
        scope=TelemetryScope(tenant="rt_clean"),
    ).run()
    scope = TelemetryScope(tenant="rt_flaky")
    session = FedSession(
        _cfg(send_retries=6, send_fault_p=0.25, send_backoff_s=0.002),
        data, model, name="rt_flaky", scope=scope,
    )
    flaky = session.run()
    snap = scope.comm_meter.snapshot()
    assert sum(snap["send_retries"].values()) > 0
    assert sum(snap["send_gave_up"].values()) == 0
    row = session.summary_row()
    assert row["comm/retries"] > 0 and row["comm/gave_up"] == 0
    for a, b in zip(
        jax.tree_util.tree_leaves(clean.global_vars),
        jax.tree_util.tree_leaves(flaky.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fedbuff_flaky_transport_completes_with_retries():
    """Async path: at-least-once re-deliveries under chaos are absorbed
    by the dispatch-tag dedupe; the run reaches its step target."""
    from fedml_tpu.serve import FedSession

    data, model = _data_model()
    scope = TelemetryScope(tenant="rt_async")
    session = FedSession(
        _cfg(send_retries=6, send_fault_p=0.2, send_backoff_s=0.002).replace(
            fed=FedConfig(
                client_num_in_total=6, client_num_per_round=2, comm_round=4,
                epochs=1, frequency_of_the_test=100, async_buffer_k=2,
            )
        ),
        data, model, name="rt_async", algorithm="fedbuff", scope=scope,
    )
    server = session.run()
    assert server.server_steps == 4
    snap = scope.comm_meter.snapshot()
    assert sum(snap["send_retries"].values()) > 0
    assert sum(snap["send_gave_up"].values()) == 0


def test_cli_rejects_chaos_without_retries_and_sim_runtimes():
    from click.testing import CliRunner

    from fedml_tpu.cli import main

    r = CliRunner().invoke(main, [
        "--runtime", "loopback", "--send_fault_p", "0.2",
        "--dataset", "synthetic", "--ci",
    ])
    assert r.exit_code != 0 and "send_retries" in r.output
    r = CliRunner().invoke(main, [
        "--runtime", "vmap", "--send_retries", "3",
        "--dataset", "synthetic", "--ci",
    ])
    assert r.exit_code != 0 and "transport" in r.output
