"""Trace-replay chaos (scheduler/faults.py + telemetry/health.py):
device-profile fleets, scripted plans, FaultPlan JSON/pickle round-trips,
and the record -> replay -> survive loop — a recorded FaultTrace replays
with byte-identical faults/* rows and numerics."""

import json
import pickle

import jax
import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.scheduler import (
    DEVICE_PROFILES,
    DeviceProfile,
    FaultInjector,
    FaultPlan,
    FaultTrace,
)
from fedml_tpu.telemetry.health import ClientHealthRegistry


def _decisions(plan, clients=8, rounds=6):
    return [
        plan.decide(c, r) for c in range(clients) for r in range(rounds)
    ]


# ---------------------------------------------------------------------------
# device profiles + fleet shorthand
# ---------------------------------------------------------------------------


def test_profile_name_as_client_spec():
    plan = FaultPlan.from_json({
        "clients": {"2": "lowend_phone", "3": {"profile": "midrange_phone",
                                               "dropout_p": 0.5}},
    })
    low = DEVICE_PROFILES["lowend_phone"]
    assert plan.spec_for(2).slowdown_s == low.slowdown_s
    assert plan.spec_for(2).dropout_p == low.dropout_p
    # overrides layer on top of the profile
    assert plan.spec_for(3).dropout_p == 0.5
    assert plan.spec_for(3).slowdown_s == DEVICE_PROFILES["midrange_phone"].slowdown_s


def test_custom_profiles_and_unknown_profile_rejected():
    plan = FaultPlan.from_json({
        "profiles": {"glacial": {"slowdown_s": 1.5, "dropout_p": 0.3}},
        "clients": {"0": "glacial"},
    })
    assert plan.spec_for(0).slowdown_s == 1.5
    with pytest.raises(ValueError, match="unknown device profile"):
        FaultPlan.from_json({"clients": {"0": "no_such_tier"}})
    # a profile may ALIAS (or derive from) a built-in tier
    plan = FaultPlan.from_json({
        "profiles": {"fast": "highend_phone",
                     "worse": {"profile": "lowend_phone", "dropout_p": 0.5}},
        "clients": {"0": "fast", "1": "worse"},
    })
    assert plan.spec_for(0) == DEVICE_PROFILES["highend_phone"].spec()
    assert plan.spec_for(1).dropout_p == 0.5
    assert plan.spec_for(1).slowdown_s == DEVICE_PROFILES["lowend_phone"].slowdown_s


def test_fleet_assignment_is_deterministic_and_apportioned():
    doc = {
        "seed": 5,
        "fleet": {"lowend_phone": 0.25, "midrange_phone": 0.25,
                  "server_grade": 0.5},
        "num_clients": 16,
    }
    a, b = FaultPlan.from_json(doc), FaultPlan.from_json(doc)
    assert {c: s for c, s in a.clients.items()} == {
        c: s for c, s in b.clients.items()
    }
    by_tier = {}
    for spec in a.clients.values():
        by_tier[spec.slowdown_s] = by_tier.get(spec.slowdown_s, 0) + 1
    low = DEVICE_PROFILES["lowend_phone"].slowdown_s
    mid = DEVICE_PROFILES["midrange_phone"].slowdown_s
    assert by_tier == {low: 4, mid: 4, 0.0: 8}
    # a different seed shuffles WHICH clients land in each tier
    other = FaultPlan.from_json({**doc, "seed": 6})
    assert {c: s.slowdown_s for c, s in a.clients.items()} != {
        c: s.slowdown_s for c, s in other.clients.items()
    }


def test_fleet_requires_num_clients_and_known_profiles():
    with pytest.raises(ValueError, match="num_clients"):
        FaultPlan.from_json({"fleet": {"lowend_phone": 1.0}})
    with pytest.raises(ValueError, match="unknown profile"):
        FaultPlan.from_json({"fleet": {"nope": 1.0}, "num_clients": 4})
    with pytest.raises(ValueError, match="num_clients"):
        FaultPlan.from_json({"num_clients": 4})


# ---------------------------------------------------------------------------
# scripted plans
# ---------------------------------------------------------------------------


def test_scripted_events_are_exact_not_probabilistic():
    plan = FaultPlan.from_json({
        "scripted": {"1": {"0": {"drop": True}, "2": {"flaky": True},
                           "3": {"slowdown_s": 0.25}}},
        "clients": {"1": {"dropout_p": 1.0}},  # overridden by the script
    })
    assert plan.decide(1, 0).drop
    assert plan.decide(1, 1).participates  # dropout_p=1 does NOT fire
    assert plan.decide(1, 2).flaky
    assert plan.decide(1, 3).slowdown_s == 0.25
    assert plan.decide(2, 0).participates  # unscripted clients untouched
    assert plan.has_participation_faults()
    assert not FaultPlan.from_json(
        {"scripted": {"1": {"0": {"flaky": True}}}}
    ).has_participation_faults()
    with pytest.raises(ValueError, match="unknown keys"):
        FaultPlan.from_json({"scripted": {"1": {"0": {"explode": True}}}})


# ---------------------------------------------------------------------------
# round-trips (satellite: to_json/from_json + pickled-decide purity fuzz)
# ---------------------------------------------------------------------------


def _rich_plan():
    return FaultPlan.from_json({
        "seed": 13,
        "default": {"dropout_p": 0.1},
        "profiles": {"glacial": {"slowdown_s": 0.7, "flaky_upload_p": 0.2}},
        "fleet": {"glacial": 0.5, "highend_phone": 0.5},
        "num_clients": 8,
        "clients": {"3": {"profile": "lowend_phone", "crash_at_round": 4},
                    "5": "midrange_phone"},
        "scripted": {"6": {"1": {"drop": True},
                           "4": {"slowdown_s": 0.05, "flaky": True}}},
    })


def test_json_roundtrip_preserves_decisions_including_profiles():
    plan = _rich_plan()
    doc = plan.to_json()
    back = FaultPlan.from_json(json.loads(json.dumps(doc)))
    assert _decisions(back) == _decisions(plan)
    assert back.to_json() == doc  # canonical form is a fixed point


def test_decide_pure_across_pickle_roundtrip_fuzz():
    """The satellite fuzz check: decide stays pure in (plan seed, client,
    round) across a pickle round-trip — per-pair draw streams cannot
    depend on process state the pickle would lose."""
    plan = _rich_plan()
    clone = pickle.loads(pickle.dumps(plan))
    rng = np.random.default_rng(0)
    for _ in range(500):
        c = int(rng.integers(0, 64))
        r = int(rng.integers(0, 256))
        assert plan.decide(c, r) == clone.decide(c, r), (c, r)
    # and across a json round-trip of the pickled clone, for good measure
    back = FaultPlan.from_json(clone.to_json())
    for _ in range(200):
        c = int(rng.integers(0, 64))
        r = int(rng.integers(0, 256))
        assert plan.decide(c, r) == back.decide(c, r), (c, r)


# ---------------------------------------------------------------------------
# fault traces: export -> from_trace -> byte-identical replay
# ---------------------------------------------------------------------------


def test_health_registry_exports_fault_events_with_detail():
    reg = ClientHealthRegistry()
    inj = FaultInjector(
        FaultPlan.from_json({"clients": {"1": {"slowdown_s": 0.3}}}),
        health=reg,
    )
    inj.record(1, 0, "slowdown", detail=0.3)
    inj.record(1, 2, "dropout")
    inj.record(2, 1, "crash")
    inj.record(2, 3, "crash")  # deduped: one crash event per client
    trace = reg.export_trace(rounds=4)
    assert trace.rounds == 4
    assert trace.clients[1]["faults"]["slowdown"] == [[0, 0.3]]
    assert trace.clients[1]["faults"]["dropout"] == [[2, 0.0]]
    assert trace.clients[2]["faults"]["crash"] == [[1, 0.0]]
    assert trace.clients[1]["trace_complete"]


def test_from_trace_builds_exact_replay_plan():
    trace = FaultTrace(rounds=6, clients={
        1: {"faults": {"dropout": [[0, 0.0], [3, 0.0]],
                       "slowdown": [[2, 0.4]]}},
        2: {"faults": {"crash": [[4, 0.0]]}},
    })
    plan = FaultPlan.from_trace(trace)
    assert plan.decide(1, 0).drop and plan.decide(1, 3).drop
    assert plan.decide(1, 1).participates
    assert plan.decide(1, 2).slowdown_s == 0.4
    assert plan.decide(2, 4).crashed and plan.decide(2, 5).crashed
    assert not plan.decide(2, 3).crashed
    assert plan.has_participation_faults()
    # truncated traces refuse to replay
    bad = FaultTrace(rounds=2, clients={
        1: {"faults": {"dropout": [[0, 0.0]]}, "trace_complete": False},
    })
    with pytest.raises(ValueError, match="truncated"):
        FaultPlan.from_trace(bad)


def test_trace_save_load_roundtrip(tmp_path):
    trace = FaultTrace(rounds=3, clients={
        0: {"faults": {"flaky": [[1, 0.0]]}, "mean_train_s": 0.01},
    })
    p = tmp_path / "trace.json"
    trace.save(str(p))
    back = FaultTrace.load(str(p))
    assert back.to_json() == trace.to_json()
    # the from_spec trace: prefix resolves through the same loader
    plan = FaultPlan.from_spec(f"trace:{p}")
    assert plan.decide(0, 1).flaky
    with pytest.raises(ValueError, match="does not exist"):
        FaultPlan.from_spec("trace:/no/such/file.json")


def _data_model():
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models import create_model

    data = synthetic_classification(
        num_clients=6, num_classes=3, feat_shape=(10,),
        samples_per_client=24, partition_method="homo", seed=0,
    )
    return data, create_model("lr", "synthetic", (10,), 3)


def _cfg(plan: str):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3, comm_round=4,
            epochs=1, frequency_of_the_test=100, fault_plan=plan,
            deadline_s=5.0, min_clients=1,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def test_record_then_replay_is_byte_identical(tmp_path):
    """THE record -> replay loop, end to end on the loopback transport: a
    probabilistically-faulted run is recorded by the server health
    registry; FaultPlan.from_trace replays it with byte-identical
    faults/* summary rows AND bit-identical numerics (ci.sh chaos gate
    b, as a test)."""
    from fedml_tpu.serve import FedSession

    data, model = _data_model()
    plan = json.dumps({
        "seed": 2,
        "default": {"dropout_p": 0.3},
        "clients": {"1": {"slowdown_s": 0.02}},
    })
    rec = FedSession(_cfg(plan), data, model, name="chaos_rec")
    rec_server = rec.run()
    rec_row = rec._injector.summary_row()
    assert rec_row["faults/total"] > 0, "recording run injected nothing"
    trace_path = tmp_path / "fault_trace.json"
    rec_server.health.export_trace(rounds=4).save(str(trace_path))

    rep = FedSession(
        _cfg(f"trace:{trace_path}"), data, model, name="chaos_rep"
    )
    rep_server = rep.run()
    assert rep._injector.summary_row() == rec_row
    for a, b in zip(
        jax.tree_util.tree_leaves(rec_server.global_vars),
        jax.tree_util.tree_leaves(rep_server.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fedbuff_run_writes_no_fault_trace(tmp_path):
    """FedBuff fault events are keyed by dispatch tag, not round — such a
    trace cannot replay faithfully, so the CLI must not export one (the
    health snapshot still lands in health.json)."""
    from click.testing import CliRunner

    from fedml_tpu.cli import main

    tdir = tmp_path / "tel"
    r = CliRunner().invoke(main, [
        "--algorithm", "fedbuff", "--runtime", "loopback", "--model", "lr",
        "--dataset", "synthetic", "--client_num_in_total", "4",
        "--client_num_per_round", "2", "--comm_round", "2",
        "--async_buffer_k", "2", "--batch_size", "8",
        "--telemetry_dir", str(tdir),
    ], catch_exceptions=False)
    assert r.exit_code == 0, r.output
    assert (tdir / "health.json").exists()
    assert not (tdir / "fault_trace.json").exists()


def test_device_profile_fleet_runs_on_vmap_simulator():
    """Participation faults from a profile fleet drive the vmap cohort
    filter — the fleet description is runtime-agnostic."""
    from fedml_tpu.algorithms import FedAvgAPI

    data, model = _data_model()
    plan = json.dumps({
        "seed": 1,
        "fleet": {"lowend_phone": 0.5, "server_grade": 0.5},
        "num_clients": 6,
    })
    config = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=4, comm_round=6,
            epochs=1, frequency_of_the_test=100, fault_plan=plan,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )
    api = FedAvgAPI(config, data, model, task="classification")
    api.train()
    assert api.faults is not None
    row = api.faults.summary_row()
    assert row["faults/dropouts"] > 0  # lowend tier really dropped
