"""Pallas flash attention (ops/flash_attention.py) vs plain softmax
attention: forward exactness and full VJP (dq/dk/dv) through the custom
backward kernels. Runs in interpret mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops import flash_attention


def _ref_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(jnp.float32(d))
    if causal:
        S, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((S, Sk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v)


def _qkv(shape, seed=0):
    rng = np.random.default_rng(seed)
    q, k, v = (
        jnp.asarray(rng.normal(size=shape).astype(np.float32)) for _ in range(3)
    )
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = _qkv((2, 2, 128, 32))  # [B, H, S, d]
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_uneven_blocks_and_single_block():
    q, k, v = _qkv((1, 192, 16), seed=3)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = _ref_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # S smaller than the block: block clamps to S
    q, k, v = _qkv((1, 32, 16), seed=4)
    out = flash_attention(q, k, v, causal=False)
    ref = _ref_attention(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_vjp_matches_reference(causal):
    q, k, v = _qkv((2, 128, 32), seed=7)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        return jnp.sum(jnp.sin(out))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_ref_attention(q, k, v, causal)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5,
            err_msg=f"d{name} mismatch",
        )


def test_transformer_lm_with_flash_attention():
    """flash_attention_bthd is a drop-in attn_fn for TransformerLM: logits
    and gradients match the full-attention module."""
    from fedml_tpu.models.transformer import TransformerLM
    from fedml_tpu.ops import flash_attention_bthd

    V, B, T = 50, 2, 128
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)

    def make(attn_fn=None):
        kw = dict(vocab_size=V, num_layers=1, num_heads=2, embed_dim=32,
                  max_len=T)
        if attn_fn is not None:
            kw["attn_fn"] = attn_fn
        return TransformerLM(**kw)

    ref_model = make()
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    flash_model = make(
        lambda q, k, v: flash_attention_bthd(q, k, v, block_q=64, block_k=64)
    )
    ref_logits = ref_model.apply(params, tokens)
    flash_logits = flash_model.apply(params, tokens)
    np.testing.assert_allclose(
        np.asarray(flash_logits), np.asarray(ref_logits), atol=1e-4
    )

    def loss(model, p):
        logits = model.apply(p, tokens)
        return jnp.mean(
            jnp.sum(jax.nn.log_softmax(logits) ** 2, axis=-1)
        )

    g_ref = jax.grad(lambda p: loss(ref_model, p))(params)
    g_flash = jax.grad(lambda p: loss(flash_model, p))(params)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_ref), jax.tree_util.tree_leaves(g_flash)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_shape_guards():
    q, k, v = _qkv((1, 100, 16))
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64)
    q, k, v = _qkv((1, 128, 16))
    k2 = k[:, :64]
    with pytest.raises(ValueError):
        flash_attention(q, k2, v[:, :64], causal=True, block_q=64, block_k=64)
