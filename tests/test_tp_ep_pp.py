"""Tensor/expert/pipeline parallelism (parallel/{tensor_parallel,
expert_parallel,pipeline}.py): each sharded program must match its
single-device oracle — TP/EP vs the same model unsharded, PP vs sequential
stage application — and train (loss decreases)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from fedml_tpu.parallel.expert_parallel import MoELM, make_ep_train_step
from fedml_tpu.parallel.pipeline import (
    make_pipeline_fn,
    make_pp_train_step,
    sequential_apply,
    stack_stage_params,
)
from fedml_tpu.parallel.tensor_parallel import make_tp_train_step, tp_param_specs

V, B, T = 32, 4, 16


def _tokens(seed=0):
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
    return toks, jnp.roll(toks, -1, axis=1)


def _mesh(axes):
    n = int(np.prod([s for _, s in axes]))
    devs = np.array(jax.devices()[:n]).reshape([s for _, s in axes])
    return Mesh(devs, [a for a, _ in axes])


def test_tp_matches_single_device():
    from fedml_tpu.models.transformer import TransformerLM
    import optax

    toks, tgts = _tokens()
    mesh = _mesh([("tp", 4)])
    init, step = make_tp_train_step(
        mesh, V, lr=1e-2, num_layers=2, num_heads=4, embed_dim=32, max_len=T
    )
    params, opt_state = init(jax.random.PRNGKey(0), toks)

    # oracle: identical params, plain single-device step
    model = TransformerLM(
        vocab_size=V, num_layers=2, num_heads=4, embed_dim=32, max_len=T
    )
    ref_params = jax.device_get(params)

    def ref_loss(p):
        logits = model.apply({"params": p}, toks)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, tgts)
        )

    ref = float(ref_loss(ref_params))
    params, opt_state, loss = step(params, opt_state, toks, tgts)
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)
    # the sharded step trains
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, toks, tgts)
    assert float(loss) < ref
    # param layout really is TP: qkv kernel sharded over tp
    qkv = params["block0"]["qkv"]["kernel"]
    assert "tp" in str(qkv.sharding.spec)


def test_ep_matches_single_device():
    import optax

    toks, tgts = _tokens(1)
    mesh = _mesh([("ep", 4)])
    init, step = make_ep_train_step(
        mesh, V, lr=1e-2, num_layers=1, num_heads=2, embed_dim=16,
        num_experts=4, max_len=T, aux_coef=0.01,
    )
    params, opt_state = init(jax.random.PRNGKey(0), toks)

    model = MoELM(
        vocab_size=V, num_layers=1, num_heads=2, embed_dim=16,
        num_experts=4, max_len=T,
    )
    ref_params = jax.device_get(params)
    logits, aux = model.apply({"params": ref_params}, toks)
    ref = float(
        jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, tgts)
        )
        + 0.01 * aux
    )
    params, opt_state, loss = step(params, opt_state, toks, tgts)
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, toks, tgts)
    assert float(loss) < ref
    w1 = params["block0"]["moe"]["w1"]
    assert "ep" in str(w1.sharding.spec)


def test_ep_expert_count_validation():
    mesh = _mesh([("ep", 4)])
    with pytest.raises(ValueError, match="divisible"):
        make_ep_train_step(mesh, V, num_experts=6)


def test_pipeline_matches_sequential():
    width, hidden, M, mb = 8, 16, 6, 4
    mesh = _mesh([("pp", 4)])
    params = stack_stage_params(jax.random.PRNGKey(0), 4, width, hidden)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(M, mb, width)), jnp.float32
    )
    pipeline = make_pipeline_fn(mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharded = jax.device_put(params, NamedSharding(mesh, P("pp")))
    out = pipeline(sharded, x)
    ref = jax.vmap(lambda m: sequential_apply(params, m))(x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


def test_pp_train_step_learns():
    width, hidden, M, mb = 8, 16, 4, 8
    mesh = _mesh([("pp", 2)])
    init, step = make_pp_train_step(mesh, width, hidden, lr=5e-3)
    params, opt_state = init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(M, mb, width)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(M, mb, width)), jnp.float32)
    params, opt_state, first = step(params, opt_state, x, tgt)
    loss = first
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, x, tgt)
    assert float(loss) < 0.7 * float(first)


def test_moe_composes_with_sequence_parallel():
    """TransformerLM(moe_experts=E) under the ring-attention SP trainer:
    the sharded loss must equal the single-device MoE LM loss EXACTLY
    (ring attention is exact; MoEMLP pmeans the routing stats over the
    seq axis before forming the Switch aux product, so the aux is the
    global load-balance loss, not a biased mean of per-shard products)."""
    import optax
    from fedml_tpu.models.transformer import TransformerLM
    from fedml_tpu.parallel.long_context import make_sp_train_step

    toks, tgts = _tokens(2)
    mesh = _mesh([("seq", 4)])
    init, step = make_sp_train_step(
        mesh, V, lr=1e-2, num_layers=1, num_heads=2, embed_dim=16,
        max_len=T, moe_experts=2, aux_coef=0.01,
    )
    params, opt_state = init(jax.random.PRNGKey(5), toks)

    model = TransformerLM(
        vocab_size=V, num_layers=1, num_heads=2, embed_dim=16, max_len=T,
        moe_experts=2,
    )
    logits, aux = model.apply({"params": jax.device_get(params)}, toks)
    ref = float(
        jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, tgts)
        )
        + 0.01 * aux
    )
    params, opt_state, loss = step(params, opt_state, toks, tgts)
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)
