"""Continuous federation service (fedml_tpu/serve/): session lifecycle,
multi-tenant isolation, elastic fleets, rolling checkpoint resume through
the session object, and the per-tenant ops surface.

The single-run transports are exercised elsewhere (test_transport.py,
test_fedbuff.py — which now run THROUGH FedSession via the wrapper entry
points); this module covers what only the service layer adds."""

import json
import os
import time

import jax
import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.serve import FedSession, FederationServer
from fedml_tpu.telemetry import (
    TelemetryScope,
    TenantedRegistryView,
    get_comm_meter,
    get_global_tracer,
)


def _data(num_clients=6, seed=0):
    return synthetic_classification(
        num_clients=num_clients, num_classes=3, feat_shape=(10,),
        samples_per_client=24, partition_method="homo", seed=seed,
    )


def _model():
    return create_model("lr", "synthetic", (10,), 3)


def _sync_cfg(comm_round=3, workers=3, total=6, seed=0, **fed_kw):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=total, client_num_per_round=workers,
            comm_round=comm_round, epochs=1, frequency_of_the_test=100,
            **fed_kw,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=seed,
    )


def _async_cfg(comm_round=4, workers=2, total=6, k=2, seed=0, **fed_kw):
    return _sync_cfg(
        comm_round=comm_round, workers=workers, total=total, seed=seed,
        async_buffer_k=k, **fed_kw,
    )


def _tree_equal(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def _spin(pred, what, timeout=60.0):
    t0 = time.monotonic()
    while not pred():
        assert time.monotonic() - t0 < timeout, f"timed out waiting for {what}"
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# telemetry isolation
# ---------------------------------------------------------------------------


def test_scoped_session_isolates_telemetry_from_globals():
    """A scoped session's spans/comm bytes land in ITS scope; the process
    globals — what every single-run path and test observes — stay
    untouched (the instance-scoping contract of the serve subsystem)."""
    data, model = _data(), _model()
    g_events = len(get_global_tracer().events())
    g_msgs = sum(get_comm_meter().snapshot()["messages_sent"].values())
    scope = TelemetryScope(tenant="iso")
    session = FedSession(
        _sync_cfg(), data, model, name="iso", scope=scope,
    )
    server = session.run()
    assert len(server.history) == 3
    # scope observed the federation...
    names = {e.name for e in scope.tracer.events()}
    assert {"round", "broadcast", "aggregate", "local_train"} <= names
    snap = scope.comm_meter.snapshot()
    assert sum(snap["messages_sent"].values()) > 0
    assert sum(snap["bytes_sent"].values()) > 0
    # ...the globals did not
    assert len(get_global_tracer().events()) == g_events
    assert (
        sum(get_comm_meter().snapshot()["messages_sent"].values()) == g_msgs
    )
    # per-tenant health registry lives in the scope's registry
    assert scope.registry.get("fedml_clients_seen") is not None


def test_unscoped_session_inherits_globals():
    """Without a scope the session records into the process globals —
    run_federation's classic behavior (byte-compat for every single-run
    caller, incl. the CLI's --telemetry_dir trace)."""
    data, model = _data(), _model()
    g_tracer = get_global_tracer()
    before = len(g_tracer.events())
    session = FedSession(_sync_cfg(comm_round=2), data, model)
    session.run()
    new = [e.name for e in g_tracer.events()[before:]]
    assert "round" in new and "aggregate" in new


# ---------------------------------------------------------------------------
# many tenants, one process
# ---------------------------------------------------------------------------


def test_federation_server_runs_concurrent_tenants_with_labeled_metrics():
    data, model = _data(), _model()
    srv = FederationServer()
    a = srv.create_session(
        "alpha", _sync_cfg(comm_round=3), data, model, algorithm="fedavg"
    )
    b = srv.create_session(
        "beta", _async_cfg(comm_round=4), data, model, algorithm="fedbuff"
    )
    srv.start()
    results = srv.wait()
    assert results["alpha"]["ok"] and results["beta"]["ok"], results
    assert len(a.history) == 3
    assert b.server.server_steps == 4
    # both tenants' comm traffic accounted separately
    for s in (a, b):
        assert sum(s.scope.comm_meter.snapshot()["messages_sent"].values()) > 0
    # one exposition, tenant labels, exactly one TYPE block per metric
    out = srv.render_metrics()
    assert 'tenant="alpha"' in out and 'tenant="beta"' in out
    sent = [
        ln for ln in out.splitlines()
        if ln.startswith("fedml_comm_messages_sent_total{")
    ]
    assert any('tenant="alpha"' in ln for ln in sent)
    assert any('tenant="beta"' in ln for ln in sent)
    assert out.count("# TYPE fedml_comm_messages_sent_total counter") == 1
    srv.close()


def test_cross_tenant_program_sharing_zero_recompiles():
    """The substrate the service exploits: co-tenant federations of the
    same model family share ONE ProgramCache — the second tenant builds
    no new programs and (when jax.monitoring is present) triggers zero
    backend compiles attributed to its scope, which is the ci.sh soak
    gate's `compile/recompiles == 0`."""
    from fedml_tpu.analysis.sentinel import ensure_backend_listener
    from fedml_tpu.compile import get_program_cache

    data, model = _data(), _model()
    have_monitoring = ensure_backend_listener()
    srv = FederationServer()
    a = srv.create_session(
        "fam_a", _async_cfg(comm_round=3, seed=0), data, model,
        algorithm="fedbuff",
    )
    srv.start(names=["fam_a"])
    a.wait()
    stats_before = get_program_cache().stats()
    b = srv.create_session(
        "fam_b", _async_cfg(comm_round=3, seed=1), data, model,
        algorithm="fedbuff",
    )
    srv.start(names=["fam_b"])
    b.wait()
    stats_after = get_program_cache().stats()
    # tenant B minted no new program objects — pure dedup hits
    assert stats_after["misses"] == stats_before["misses"]
    assert stats_after["hits"] > stats_before["hits"]
    if have_monitoring:
        assert b.scope.recompiles() == 0, b.scope.recompiles()
    srv.close()


def test_tenanted_registry_view_merges_blocks():
    """Same metric name across tenants renders as ONE HELP/TYPE block
    with per-tenant sample lines (strict exposition-format parsers
    reject duplicate blocks)."""
    from fedml_tpu.telemetry import MetricsRegistry

    ra, rb = MetricsRegistry(), MetricsRegistry()
    ra.counter("svc_total", "h", ("k",)).inc(1, k="x")
    rb.counter("svc_total", "h", ("k",)).inc(2, k="x")
    rb.histogram("svc_seconds", "h", buckets=(1.0,)).observe(0.5)
    view = TenantedRegistryView()
    view.add_tenant("a", ra)
    view.add_tenant("b", rb)
    out = view.render()
    assert out.count("# TYPE svc_total counter") == 1
    assert 'svc_total{k="x",tenant="a"} 1.0' in out
    assert 'svc_total{k="x",tenant="b"} 2.0' in out
    assert 'svc_seconds_bucket{tenant="b",le="1.0"} 1.0' in out
    assert 'svc_seconds_count{tenant="b"} 1.0' in out


# ---------------------------------------------------------------------------
# elastic fleets (FedBuff)
# ---------------------------------------------------------------------------


def test_elastic_join_leave_with_backpressure():
    data, model = _data(num_clients=8), _model()
    session = FedSession(
        _async_cfg(comm_round=40, workers=2, total=8), data, model,
        algorithm="fedbuff", max_workers=3,
    )
    session.start()
    _spin(lambda: session.server.server_steps >= 3, "first steps")
    joined = session.add_worker()  # fleet 2 -> 3: admitted
    _spin(lambda: session.server.joins_accepted >= 1, "join accept")
    refused = session.add_worker()  # fleet at max_workers: refused
    _spin(lambda: session.server.joins_refused >= 1, "join refuse")
    left = session.remove_worker()
    assert left is joined  # highest-rank live worker
    _spin(lambda: session.server.leaves >= 1, "leave")
    server = session.wait()
    assert server.server_steps == 40
    assert server.joins_accepted == 1
    assert server.joins_refused == 1
    assert server.leaves == 1
    # backpressure is graceful: the refused worker got FINISH, it is
    # neither orphaned nor an error
    assert refused._got_finish and not refused.orphaned
    assert left.left
    st = session.status()
    assert st["state"] == "done" and st["joins_refused"] == 1


def test_sync_session_rejects_elastic_ops():
    data, model = _data(), _model()
    session = FedSession(_sync_cfg(comm_round=2), data, model)
    with pytest.raises(RuntimeError, match="FedBuff"):
        session.add_worker()


def test_refused_join_is_not_counted_live_later():
    """A refused joiner must not haunt the live count: once later
    admissions grow worker_num past its rank, an uncounted phantom would
    make the fleet permanently appear fuller than it is and refuse joins
    below max_workers forever."""
    data, model = _data(num_clients=8), _model()
    session = FedSession(
        _async_cfg(comm_round=10_000, workers=2, total=8), data, model,
        algorithm="fedbuff", max_workers=3,
    )
    session.start()
    srv = session.server
    _spin(lambda: srv.server_steps >= 2, "steps")
    session.add_worker()                       # rank 3: live 2 -> 3
    _spin(lambda: srv.joins_accepted >= 1, "admit rank 3")
    session.add_worker()                       # rank 4: at max -> refused
    _spin(lambda: srv.joins_refused >= 1, "refuse rank 4")
    session.remove_worker()                    # rank 3 leaves: live 2
    _spin(lambda: srv.leaves >= 1, "rank 3 leave")
    session.add_worker()                       # rank 5: live 2 -> 3
    _spin(lambda: srv.joins_accepted >= 2, "admit rank 5")
    session.remove_worker()                    # rank 5 leaves: live 2
    _spin(lambda: srv.leaves >= 2, "rank 5 leave")
    # worker_num is now 5 and the refused rank 4 never joined: a correct
    # live count reads 2 (< max_workers), so this join MUST be admitted
    session.add_worker()
    _spin(lambda: srv.joins_accepted >= 3, "admit after phantom")
    assert srv.joins_refused == 1
    session.drain()
    session.wait(timeout=60)


def test_fedbuff_rejects_warmup():
    data, model = _data(), _model()
    with pytest.raises(ValueError, match="warmup"):
        FedSession(
            _async_cfg(), data, model, algorithm="fedbuff", warmup=True
        )


def test_failed_build_cleans_up_and_marks_failed():
    """A misconfigured tenant (participation faults without deadline_s)
    must fail at start() WITHOUT leaking the shm tmpdir its default comm
    factory already created — a long-lived service admits many specs."""
    data, model = _data(), _model()
    session = FedSession(
        _sync_cfg(comm_round=2, fault_plan='{"default": {"dropout_p": 0.5}}'),
        data, model, runtime="shm",
    )
    with pytest.raises(ValueError, match="deadline_s"):
        session.start()
    assert session.state == "failed"
    assert session._tmpdir is None  # removed, not leaked


# ---------------------------------------------------------------------------
# drain / stop
# ---------------------------------------------------------------------------


def test_fedbuff_drain_stops_early_and_cleanly():
    data, model = _data(), _model()
    session = FedSession(
        _async_cfg(comm_round=10_000), data, model, algorithm="fedbuff"
    )
    session.start()
    _spin(lambda: session.server.server_steps >= 2, "steps")
    session.drain()
    server = session.wait(timeout=60)
    assert 2 <= server.server_steps < 10_000
    assert session.state == "done"


def test_sync_drain_finishes_open_round_then_stops():
    data, model = _data(), _model()
    hit = []

    def log_fn(row):
        if row.get("round") == 1 and "t_s" in row:
            hit.append(row)
            session.request_stop(drain=True, defer=True)

    session = FedSession(
        _sync_cfg(comm_round=10_000), data, model, log_fn=log_fn
    )
    session.start()
    server = session.wait(timeout=120)
    assert hit, "round 1 never completed"
    # the round that carried the stop completed; no further round opened
    assert server.round_idx == 2
    assert session.state == "done"
    # a redundant hard stop on the finished server is a no-op: no
    # fabricated zero-upload round, no duplicate FINISH storm
    rounds_before = len(server.history)
    session.stop()
    assert len(server.history) == rounds_before
    assert server.round_idx == 2


# ---------------------------------------------------------------------------
# rolling checkpoints + resume through the session object (satellite)
# ---------------------------------------------------------------------------


def _instrumented_dispatch(monkeypatch, seq):
    """Record every freshly-minted FedBuff assignment as (client, tag)."""
    from fedml_tpu.algorithms.fedbuff import FedBuffServerManager

    orig = FedBuffServerManager._dispatch

    def patched(self, worker, msg_type=None, reuse=False):
        if msg_type is None:
            r = orig(self, worker, reuse=reuse)
        else:
            r = orig(self, worker, msg_type, reuse)
        if not reuse and worker in self._outstanding:
            seq.append(tuple(self._outstanding[worker]))
        return r

    monkeypatch.setattr(FedBuffServerManager, "_dispatch", patched)
    return orig


def test_fedbuff_session_kill_and_resume_matches_uninterrupted(
    tmp_path, monkeypatch
):
    """THE serve resume contract, through the session object: kill a
    FedBuff session mid-run (deferred hard stop at step 3, rolling
    checkpoint every flush), resume it, and the continuation must (a)
    re-mint the in-flight assignment stream byte-identically — the
    ``sched``-slot/dispatch-counter re-selection — and (b) land on
    numerics identical to an uninterrupted run. K=1 worker with
    async_buffer_k=1 makes the async pipeline fully sequential, so the
    equality is exact, not approximate. power_of_choice selection makes
    the scheduler's persisted loss map load-bearing (an empty one would
    re-select differently)."""
    data, model = _data(num_clients=8, seed=0), _model()

    def cfg():
        return _async_cfg(
            comm_round=6, workers=1, total=8, k=1, seed=3,
            selection="power_of_choice",
        )

    # uninterrupted reference run, with the dispatch stream recorded
    seq_ref = []
    _instrumented_dispatch(monkeypatch, seq_ref)
    ref = FedSession(cfg(), data, model, algorithm="fedbuff").run()
    assert ref.server_steps == 6
    assert len(seq_ref) == 6  # K=1, k=1: one fresh assignment per step
    monkeypatch.undo()

    # killed run: rolling checkpoint every flush, deferred stop at step 3
    cp = str(tmp_path / "tenant_ck")

    def kill_at_3(row):
        if row.get("server_step") == 3:
            killed.request_stop(drain=False, defer=True)

    killed = FedSession(
        cfg(), data, model, algorithm="fedbuff",
        checkpoint_path=cp, checkpoint_every=1, log_fn=kill_at_3,
    )
    dead = killed.run()
    assert dead.server_steps == 3
    assert os.path.exists(cp + ".npz")

    # resumed run: re-selects the in-flight assignment, finishes 4..6
    seq_resumed = []
    _instrumented_dispatch(monkeypatch, seq_resumed)
    resumed_session = FedSession(
        cfg(), data, model, algorithm="fedbuff",
        checkpoint_path=cp, checkpoint_every=1, resume=True,
    )
    resumed = resumed_session.run()
    monkeypatch.undo()
    assert resumed.server_steps == 6
    # (a) the in-flight cohort: the resumed stream IS the reference
    # stream's tail — same clients, same dispatch tags
    assert seq_resumed == seq_ref[3:], (seq_resumed, seq_ref)
    # (b) numerics: bit-identical to never having died
    _tree_equal(ref.global_vars, resumed.global_vars)


def test_sync_session_rolling_checkpoint_resume(tmp_path):
    """Sync path of the same contract: rolling checkpoints at round
    boundaries, resume re-selects via the scheduler's sched slot and the
    continuation matches the uninterrupted run bit-for-bit (aggregation
    sorts by worker index, so sync loopback rounds are order-independent
    and exactly reproducible)."""
    data, model = _data(num_clients=6, seed=1), _model()

    def cfg():
        return _sync_cfg(comm_round=6, workers=2, total=6, seed=7)

    ref = FedSession(cfg(), data, model).run()

    cp = str(tmp_path / "sync_ck")

    def kill_after_round_2(row):
        if row.get("round") == 2 and "t_s" in row:
            killed.request_stop(drain=True, defer=True)

    killed = FedSession(
        cfg(), data, model,
        checkpoint_path=cp, checkpoint_every=1, log_fn=kill_after_round_2,
    )
    dead = killed.run()
    assert dead.round_idx == 3  # rounds 0..2 ran

    resumed = FedSession(
        cfg(), data, model,
        checkpoint_path=cp, checkpoint_every=1, resume=True,
    ).run()
    assert resumed.round_idx == 6
    _tree_equal(ref.global_vars, resumed.global_vars)


def test_resume_of_completed_checkpoint_is_noop(tmp_path):
    data, model = _data(), _model()
    cp = str(tmp_path / "done_ck")
    FedSession(
        _sync_cfg(comm_round=2), data, model,
        checkpoint_path=cp, checkpoint_every=1,
    ).run()
    again = FedSession(
        _sync_cfg(comm_round=2), data, model,
        checkpoint_path=cp, checkpoint_every=1, resume=True,
    )
    again.start()
    server = again.wait()
    assert again.state == "done"
    assert server.history == []  # nothing re-ran


# ---------------------------------------------------------------------------
# endpoint namespacing (satellite)
# ---------------------------------------------------------------------------


def test_shm_namespace_isolates_concurrent_federations(tmp_path):
    """Two shm federations sharing ONE sock_dir must not collide: the
    namespace lands in the socket filename, so the second session's
    rank-0 listener no longer unlinks the first's. (Before the fix, the
    second constructor stole the live socket — a race, then cross-
    delivery.)"""
    from fedml_tpu.core.shm_comm import ShmCommManager, _addr
    from fedml_tpu.core.message import Message, MessageType as MT

    d = str(tmp_path)
    a0 = ShmCommManager(0, d, namespace="ses_a")
    b0 = ShmCommManager(0, d, namespace="ses_b")  # same rank, same dir
    assert _addr(d, 0, "ses_a") != _addr(d, 0, "ses_b")
    assert os.path.exists(_addr(d, 0, "ses_a"))  # a's listener survived b
    assert os.path.exists(_addr(d, 0, "ses_b"))
    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append((t, m.get("ns")))

    import threading

    a0.add_observer(Obs())
    ta = threading.Thread(target=a0.handle_receive_message, daemon=True)
    ta.start()
    a1 = ShmCommManager(1, d, namespace="ses_a")
    msg = Message(MT.C2S_SEND_STATS, 1, 0)
    msg.add_params("ns", "a")
    a1.send_message(msg)
    _spin(lambda: len(got) == 1, "namespaced delivery")
    assert got == [(MT.C2S_SEND_STATS, "a")]
    for m in (a1, a0, b0):
        m.stop_receive_message()
    ta.join(timeout=10)


def test_concurrent_shm_sessions_share_one_sock_dir(tmp_path, monkeypatch):
    """End-to-end: two shm sessions running at once, both socket dirs
    forced to the SAME directory — only the per-session namespace keeps
    them apart."""
    import tempfile

    shared = str(tmp_path / "shared_socks")
    os.makedirs(shared, exist_ok=True)
    monkeypatch.setattr(tempfile, "mkdtemp", lambda **kw: shared)
    data, model = _data(), _model()
    srv = FederationServer()
    a = srv.create_session(
        "shm_a", _sync_cfg(comm_round=2), data, model, runtime="shm"
    )
    b = srv.create_session(
        "shm_b", _sync_cfg(comm_round=2, seed=5), data, model, runtime="shm"
    )
    srv.start()
    results = srv.wait()
    assert results["shm_a"]["ok"] and results["shm_b"]["ok"], results
    assert len(a.history) == 2 and len(b.history) == 2
    srv.close()


# ---------------------------------------------------------------------------
# serve CLI
# ---------------------------------------------------------------------------


def test_serve_cli_multi_tenant_spec(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    spec = {
        "tenants": [
            {
                "name": "s1", "algorithm": "fedavg", "runtime": "loopback",
                "model": "lr", "dataset": "synthetic",
                "client_num_in_total": 6, "client_num_per_round": 3,
                "comm_round": 2, "batch_size": 8,
                "frequency_of_the_test": 2,
            },
            {
                "name": "s2", "algorithm": "fedbuff", "runtime": "loopback",
                "model": "lr", "dataset": "synthetic",
                "client_num_in_total": 6, "client_num_per_round": 2,
                "comm_round": 3, "batch_size": 8, "async_buffer_k": 2,
                "frequency_of_the_test": 100,
            },
        ]
    }
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec))
    log_dir = tmp_path / "logs"
    result = CliRunner().invoke(
        serve_main,
        ["--spec", str(spec_path), "--log_dir", str(log_dir)],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    out = json.loads(result.output.strip().splitlines()[-1])
    assert out["s1"]["ok"] and out["s2"]["ok"], out
    # aggregate summary carries per-tenant rows...
    agg = json.loads((log_dir / "summary.json").read_text())
    assert agg["tenants/s1/state"] == "done"
    assert agg["tenants/s2/server_steps"] == 3
    assert agg["tenants/s1/comm_bytes_sent"] > 0
    # ...and each tenant has its own full single-run-shaped summary
    t1 = json.loads((log_dir / "s1" / "summary.json").read_text())
    assert "Test/Acc" in t1


def test_serve_cli_rejects_bad_spec(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.serve.cli import serve_main

    bad = [{"name": "x", "algorithm": "fedavg", "no_such_flag": 1}]
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    result = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert result.exit_code != 0
    assert "no_such_flag" in result.output
    dup = [{"name": "x"}, {"name": "x"}]
    p.write_text(json.dumps(dup))
    result = CliRunner().invoke(serve_main, ["--spec", str(p)])
    assert result.exit_code != 0
