"""FedOpt and FedProx over the cross-silo transport == their vmap
simulators (the reference runs both as distributed MPI algorithms; here the
transport server applies the same jitted server step / the client trainer
the same prox-term local loss)."""

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
from fedml_tpu.config import (
    DataConfig,
    FedConfig,
    RunConfig,
    ServerConfig,
    TrainConfig,
)
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression


def _fixture(train, server=ServerConfig(), epochs=1):
    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(5,), samples_per_client=12,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),  # deterministic oracle config
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=3,
            epochs=epochs, frequency_of_the_test=3,
        ),
        train=train,
        server=server,
        seed=0,
    )
    return cfg, data, model_def


def _assert_matches(sim_vars, server_vars):
    for a, b in zip(
        jax.tree_util.tree_leaves(sim_vars),
        jax.tree_util.tree_leaves(server_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        )


@pytest.mark.recompile_budget(60)  # standalone worst case ~41; the sim and
# transport must SHARE their programs (ProgramCache), not recompile per side
def test_loopback_fedopt_matches_simulator(recompile_sentinel):
    from fedml_tpu.algorithms.fedopt import FedOptAPI

    cfg, data, model_def = _fixture(
        TrainConfig(client_optimizer="sgd", lr=0.1),
        ServerConfig(server_optimizer="adam", server_lr=0.05),
    )
    sim = FedOptAPI(cfg, data, model_def())
    sim.train()
    server = run_loopback_federation(cfg, data, model_def(), server_opt=True)
    assert server.round_idx == 3
    _assert_matches(sim.global_vars, server.global_vars)


def test_loopback_fedprox_matches_simulator():
    from fedml_tpu.algorithms import FedAvgAPI

    # epochs>1: with a single local step the prox gradient mu(w - w_g) is
    # identically zero (w == w_g), making FedProx == FedAvg trivially
    cfg, data, model_def = _fixture(
        TrainConfig(client_optimizer="sgd", lr=0.1, prox_mu=0.1), epochs=3
    )
    sim = FedAvgAPI(cfg, data, model_def())
    sim.train()
    server = run_loopback_federation(cfg, data, model_def())
    _assert_matches(sim.global_vars, server.global_vars)
    # and the prox term actually changed the trajectory vs plain FedAvg
    cfg0, data0, model_def0 = _fixture(
        TrainConfig(client_optimizer="sgd", lr=0.1), epochs=3
    )
    plain = FedAvgAPI(cfg0, data0, model_def0())
    plain.train()
    diffs = [
        np.max(np.abs(np.asarray(a) - np.asarray(b)))
        for a, b in zip(
            jax.tree_util.tree_leaves(sim.global_vars),
            jax.tree_util.tree_leaves(plain.global_vars),
        )
    ]
    assert max(diffs) > 1e-4
