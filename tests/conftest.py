"""Test config: run everything on a virtual 8-device CPU mesh so multi-chip
sharding logic is exercised without TPU hardware (SURVEY §7 / task spec)."""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
