"""Test config: run everything on a virtual 8-device CPU mesh so multi-chip
sharding logic is exercised without TPU hardware (SURVEY §7 / task spec)."""

import os

# Must be set before jax backend init. The container's sitecustomize may
# register a TPU backend and pin jax_platforms at interpreter startup; the
# env var alone doesn't win, so also force the config value after import.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent compilation cache: recompiles (not the math) dominate suite
# latency (VERDICT r1 weak #6); repeated runs hit the disk cache instead.
jax.config.update("jax_compilation_cache_dir", "/tmp/fedml_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running test — fast tier deselects with -m 'not slow'",
    )
