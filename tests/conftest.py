"""Test config: run everything on a virtual 8-device CPU mesh so multi-chip
sharding logic is exercised without TPU hardware (SURVEY §7 / task spec)."""

import os

# Must be set before jax backend init. The container's sitecustomize may
# register a TPU backend and pin jax_platforms at interpreter startup; the
# env var alone doesn't win, so also force the config value after import.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent compilation cache — the HARDENED wrapper (fedml_tpu/compile/
# persistent.py: atomic rename writes, sha256 integrity verification with
# quarantine, advisory file lock), so concurrent pytest processes can no
# longer poison each other's entries (the PR 3 corruption incident class).
#
# Thresholds stay CONSERVATIVE on purpose. The old aggressive config
# (min_entry_size=-1, min_compile_time=0.3) cached every tiny program and
# CORRUPTED THE HEAP on this container's jaxlib+CPU stack: cold-cache suite
# runs flaked ~40% with wrong resume numerics (a restored model evaluating
# at chance), `free(): invalid pointer` / segfaults at exit, and fatal
# "Garbage-collecting" aborts mid-run (the DARTS unrolled trace and the
# jax.profiler TF import were the usual victims — they are just the next
# malloc-heavy phase after the corruption). With the cache fully off the
# same repro loops ran clean 6/6 — but the fast tier then recompiles
# everything and blows the tier-1 time budget. Caching only slow-to-compile
# programs (>= 2 s) keeps the big wins (fused chunks, second-order DARTS,
# attention stacks) with none of the tiny-entry churn that reproduced the
# corruption; detector loops (the resume tests and the abort-prone file
# combo) ran clean under this config. The hardened store uses its own
# .ftpc entry format, so the v3 dir below never mixes with stock-format
# leftovers.
from fedml_tpu.compile import install_hardened_cache  # noqa: E402

install_hardened_cache(
    "/tmp/fedml_tpu_jax_cache_v3", min_compile_time_secs=2.0
)

# Serialized-executable store (fedml_tpu/compile/executable_cache.py),
# session-scoped: every AOT warmup in the suite exports its executable,
# and any later build of the same (program digest, shape class) — another
# test module after a cache reset, a CLI-runner run, a REPEAT pytest
# invocation on this machine — deserializes it instead of recompiling, so
# test modules stop re-paying each other's compiles. Safe by keying: the
# environment fingerprint includes a content hash of the fedml_tpu
# source, so editing ANY .py file invalidates every entry (clean miss,
# recompile) — persisted executables can never go stale against the code.
from fedml_tpu.compile import install_executable_cache  # noqa: E402

# uid-keyed path + 0700 on creation: entries are pickles (a code-trust
# boundary — see the executable_cache module docstring), so the session
# store must never be a world-writable shared /tmp directory another
# user could pre-seed.
install_executable_cache(f"/tmp/fedml_tpu_exec_cache_v1_u{os.getuid()}")


@pytest.fixture(scope="session")
def executable_cache():
    """The session's installed serialized-executable store (None when
    this jaxlib cannot serialize AOT executables — tests that need it
    should skip)."""
    from fedml_tpu.compile import installed_executable_cache

    return installed_executable_cache()


@pytest.fixture(scope="session")
def program_cache():
    """THE process-wide ProgramCache (fedml_tpu/compile/program_cache.py)
    — the same registry every round/eval/train factory dedupes through,
    exposed session-scoped so test modules share each other's compiles
    instead of recompiling structurally identical programs."""
    from fedml_tpu.compile import get_program_cache

    return get_program_cache()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running test — fast tier deselects with -m 'not slow'",
    )
    config.addinivalue_line(
        "markers",
        "recompile_budget(n): with the recompile_sentinel fixture, fail "
        "the test when more than n XLA backend compiles happen during it "
        "(fedml_tpu/analysis/sentinel.py). Budgets are coarse upper "
        "bounds — every backend compile counts, including small utility "
        "programs — sized to catch per-round recompile storms while "
        "passing standalone runs (where no earlier test pre-built the "
        "shared programs).",
    )


@pytest.fixture
def recompile_sentinel(request):
    """Runtime recompile tripwire (fedml_tpu/analysis/sentinel.py): pair
    with ``@pytest.mark.recompile_budget(n)`` — the test fails when the
    body triggers more than n XLA backend compiles. Without the marker
    the fixture only observes (``sentinel.recompiles()``)."""
    from fedml_tpu.analysis.sentinel import RecompileSentinel

    marker = request.node.get_closest_marker("recompile_budget")
    budget = int(marker.args[0]) if marker and marker.args else None
    sentinel = RecompileSentinel(
        budget=budget, label=request.node.name
    ).start()
    yield sentinel
    sentinel.stop()
    if sentinel.exceeded():
        pytest.fail(sentinel.describe())
