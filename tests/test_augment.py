"""Device-side augmentation (train/augment.py — ref CifarDataLoader
transforms + Cutout, base.py:136-146): geometry, determinism, padded-zero
invariance, and the TrainConfig.augment hook into the shared forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.train.augment import make_augment, resolve_augment


def _imgs(B=4, H=32, W=32, C=3, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(B, H, W, C)).astype(np.float32) + 1.0)


def test_shapes_and_determinism():
    aug = make_augment()
    x = _imgs()
    key = jax.random.PRNGKey(3)
    a = aug(key, x)
    b = aug(key, x)
    assert a.shape == x.shape
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = aug(jax.random.PRNGKey(4), x)
    assert np.abs(np.asarray(a) - np.asarray(c)).max() > 0


def test_cutout_zeroes_a_square():
    aug = make_augment(crop_padding=0, flip=False, cutout_size=8)
    x = _imgs()
    out = np.asarray(aug(jax.random.PRNGKey(0), x))
    for i in range(x.shape[0]):
        zeroed = (out[i] == 0).all(axis=-1)
        n = zeroed.sum()
        # full square = 64; clipped at the edge can be less, never more
        assert 0 < n <= 64
        ys, xs = np.where(zeroed)
        # zeroed region is a contiguous rectangle
        assert (ys.max() - ys.min() + 1) * (xs.max() - xs.min() + 1) == n


def test_crop_is_a_translation():
    aug = make_augment(crop_padding=2, flip=False, cutout_size=0)
    x = _imgs(B=8)
    out = np.asarray(aug(jax.random.PRNGKey(1), x))
    xn = np.asarray(x)
    padded = np.pad(xn, ((0, 0), (2, 2), (2, 2), (0, 0)))
    for i in range(8):
        found = any(
            np.array_equal(out[i], padded[i, oy : oy + 32, ox : ox + 32])
            for oy in range(5)
            for ox in range(5)
        )
        assert found


def test_padded_zero_samples_stay_zero():
    aug = resolve_augment("cifar")
    z = jnp.zeros((2, 32, 32, 3), jnp.float32)
    out = aug(jax.random.PRNGKey(0), z)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(z))


def test_train_step_with_augment_runs_and_none_is_identity():
    from fedml_tpu.config import TrainConfig
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.cnn import CNNOriginalFedAvg
    from fedml_tpu.train.client import make_local_train

    model = ModelDef(
        CNNOriginalFedAvg(num_classes=5), (28, 28, 1), 5, name="cnn"
    )
    variables = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 4, 28, 28, 1)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 5, size=(2, 4)), jnp.int32)
    m = jnp.ones((2, 4), jnp.float32)

    out = {}
    for policy in ("none", "crop_flip"):
        tc = TrainConfig(client_optimizer="sgd", lr=0.1, augment=policy)
        fn = jax.jit(make_local_train(model, tc, epochs=1))
        new_vars, metrics = fn(variables, x, y, m, jax.random.PRNGKey(7))
        assert np.isfinite(float(metrics["loss_sum"]))
        out[policy] = new_vars
    # augmentation actually changed the training trajectory
    diffs = [
        np.abs(np.asarray(a) - np.asarray(b)).max()
        for a, b in zip(
            jax.tree_util.tree_leaves(out["none"]),
            jax.tree_util.tree_leaves(out["crop_flip"]),
        )
    ]
    assert max(diffs) > 0


def test_unknown_policy_raises():
    with pytest.raises(ValueError):
        resolve_augment("mixup")
