"""Shape/param sanity for the model zoo (ref: the reference's only model test
is a param/FLOP counter, fedml_api/model/cv/test_cnn.py:1-14 — we check
init+apply shapes, dtype, and train-mode mutability instead)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models import create_model

# heavy=True cases only shape-check via jax.eval_shape (no XLA compile):
# compiling mobilenet_v3/efficientnet/etc. on the CPU test mesh costs
# 10-45 s EACH and dominated the suite (VERDICT r2 Weak #8). Execution
# coverage for the conv families is kept by the executed rows below
# (resnet56 BN, mobilenet depthwise) plus the federated integration tests
CASES = [
    # (model, dataset, input_shape, num_classes, kw, logits_shape_fn, heavy)
    ("lr", "mnist", (28, 28, 1), 10, {}, lambda B: (B, 10), False),
    ("cnn", "femnist", (28, 28, 1), 62, {}, lambda B: (B, 62), False),
    ("cnn_dropout", "femnist", (28, 28, 1), 62, {}, lambda B: (B, 62), False),
    ("rnn", "shakespeare", (20,), 90, {}, lambda B: (B, 90), False),
    ("rnn", "fed_shakespeare", (20,), 90, {}, lambda B: (B, 20, 90), False),
    ("rnn", "stackoverflow_nwp", (20,), 10004, {}, lambda B: (B, 20, 10004), True),
    ("resnet56", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10), False),
    ("resnet18_gn", "fed_cifar100", (24, 24, 3), 100, {}, lambda B: (B, 100), True),
    ("mobilenet", "cifar100", (32, 32, 3), 100, {}, lambda B: (B, 100), False),
    ("mobilenet_v3", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10), True),
    ("vgg11", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10), True),
    ("vgg16_bn", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10), True),
    ("efficientnet", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10), True),
]


@pytest.mark.parametrize(
    "name,ds,shape,classes,kw,out_fn,heavy",
    CASES,
    ids=[f"{c[0]}-{c[1]}" for c in CASES],
)
def test_model_shapes(name, ds, shape, classes, kw, out_fn, heavy):
    model = create_model(name, ds, shape, classes, **kw)
    rng = jax.random.PRNGKey(0)
    B = 2
    in_dtype = (
        jnp.int32 if model.input_dtype == jnp.int32 else jnp.float32
    )
    if heavy:
        # abstract trace: checks init/apply wiring and logits shapes for
        # BOTH modes without compiling or executing anything
        variables = jax.eval_shape(model.init, rng)
        xs = jax.ShapeDtypeStruct((B,) + shape, in_dtype)
        out, _ = jax.eval_shape(
            lambda v, x: model.apply(v, x, train=False), variables, xs
        )
        assert out.shape == out_fn(B)
        out_t, vars_train = jax.eval_shape(
            lambda v, x, r: model.apply(v, x, train=True, rng=r),
            variables,
            xs,
            jax.random.fold_in(rng, 1),
        )
        assert out_t.shape == out_fn(B)
        if model.has_batch_stats:
            assert "batch_stats" in vars_train
        return
    variables = model.init(rng)
    if in_dtype == jnp.int32:
        x = jnp.ones((B,) + shape, jnp.int32)
    else:
        x = jnp.zeros((B,) + shape, jnp.float32)
    # eval mode
    out, vars_eval = model.apply(variables, x, train=False)
    assert out.shape == out_fn(B)
    assert np.all(np.isfinite(np.asarray(out)))
    # train mode must run and (for BN models) mutate batch_stats
    out_t, vars_train = model.apply(
        variables, x, train=True, rng=jax.random.fold_in(rng, 1)
    )
    assert out_t.shape == out_fn(B)
    if model.has_batch_stats:
        assert "batch_stats" in vars_train


def test_gan_shapes():
    from fedml_tpu.models.gan import MNISTGan

    m = MNISTGan()
    z = jnp.zeros((4, 100))
    x = jnp.zeros((4, 28, 28, 1))
    variables = m.init(
        {"params": jax.random.PRNGKey(0)}, z, x, train=False
    )
    fake, d_fake, d_real = m.apply(variables, z, x, train=False)
    assert fake.shape == (4, 28, 28, 1)
    assert d_fake.shape == (4, 1) and d_real.shape == (4, 1)


def test_vfl_models():
    from fedml_tpu.models.vfl import VFLClassifier, VFLFeatureExtractor

    fe = VFLFeatureExtractor(output_dim=16)
    v = fe.init(jax.random.PRNGKey(0), jnp.zeros((3, 30)))
    feats = fe.apply(v, jnp.zeros((3, 30)))
    assert feats.shape == (3, 16)
    clf = VFLClassifier(output_dim=2)
    vc = clf.init(jax.random.PRNGKey(1), feats)
    assert clf.apply(vc, feats).shape == (3, 2)


def test_registry_unknown_raises():
    with pytest.raises(KeyError):
        create_model("nope", "mnist", (1,), 2)
