"""Shape/param sanity for the model zoo (ref: the reference's only model test
is a param/FLOP counter, fedml_api/model/cv/test_cnn.py:1-14 — we check
init+apply shapes, dtype, and train-mode mutability instead)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models import create_model

CASES = [
    # (model, dataset, input_shape, num_classes, kw, expected_logits_shape_fn)
    ("lr", "mnist", (28, 28, 1), 10, {}, lambda B: (B, 10)),
    ("cnn", "femnist", (28, 28, 1), 62, {}, lambda B: (B, 62)),
    ("cnn_dropout", "femnist", (28, 28, 1), 62, {}, lambda B: (B, 62)),
    ("rnn", "shakespeare", (20,), 90, {}, lambda B: (B, 90)),
    ("rnn", "fed_shakespeare", (20,), 90, {}, lambda B: (B, 20, 90)),
    ("rnn", "stackoverflow_nwp", (20,), 10004, {}, lambda B: (B, 20, 10004)),
    ("resnet56", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10)),
    ("resnet18_gn", "fed_cifar100", (24, 24, 3), 100, {}, lambda B: (B, 100)),
    ("mobilenet", "cifar100", (32, 32, 3), 100, {}, lambda B: (B, 100)),
    ("mobilenet_v3", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10)),
    ("vgg11", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10)),
    ("vgg16_bn", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10)),
    ("efficientnet", "cifar10", (32, 32, 3), 10, {}, lambda B: (B, 10)),
]


@pytest.mark.parametrize(
    "name,ds,shape,classes,kw,out_fn",
    CASES,
    ids=[f"{c[0]}-{c[1]}" for c in CASES],
)
def test_model_shapes(name, ds, shape, classes, kw, out_fn):
    model = create_model(name, ds, shape, classes, **kw)
    rng = jax.random.PRNGKey(0)
    variables = model.init(rng)
    B = 2
    if model.input_dtype == jnp.int32:
        x = jnp.ones((B,) + shape, jnp.int32)
    else:
        x = jnp.zeros((B,) + shape, jnp.float32)
    # eval mode
    out, vars_eval = model.apply(variables, x, train=False)
    assert out.shape == out_fn(B)
    assert np.all(np.isfinite(np.asarray(out)))
    # train mode must run and (for BN models) mutate batch_stats
    out_t, vars_train = model.apply(
        variables, x, train=True, rng=jax.random.fold_in(rng, 1)
    )
    assert out_t.shape == out_fn(B)
    if model.has_batch_stats:
        assert "batch_stats" in vars_train


def test_gan_shapes():
    from fedml_tpu.models.gan import MNISTGan

    m = MNISTGan()
    z = jnp.zeros((4, 100))
    x = jnp.zeros((4, 28, 28, 1))
    variables = m.init(
        {"params": jax.random.PRNGKey(0)}, z, x, train=False
    )
    fake, d_fake, d_real = m.apply(variables, z, x, train=False)
    assert fake.shape == (4, 28, 28, 1)
    assert d_fake.shape == (4, 1) and d_real.shape == (4, 1)


def test_vfl_models():
    from fedml_tpu.models.vfl import VFLClassifier, VFLFeatureExtractor

    fe = VFLFeatureExtractor(output_dim=16)
    v = fe.init(jax.random.PRNGKey(0), jnp.zeros((3, 30)))
    feats = fe.apply(v, jnp.zeros((3, 30)))
    assert feats.shape == (3, 16)
    clf = VFLClassifier(output_dim=2)
    vc = clf.init(jax.random.PRNGKey(1), feats)
    assert clf.apply(vc, feats).shape == (3, 2)


def test_registry_unknown_raises():
    with pytest.raises(KeyError):
        create_model("nope", "mnist", (1,), 2)
