"""FedNAS/DARTS: mixture network shapes, genotype derivation, federated
search round averaging both weights and architecture params."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models.darts import (
    DARTSNetwork,
    DEFAULT_OPS,
    derive_genotype,
    num_edges,
)


def test_darts_network_forward():
    net = DARTSNetwork(num_classes=5, ch=8, cells=2, steps=2)
    v = net.init({"params": jax.random.PRNGKey(0)}, jnp.zeros((2, 8, 8, 3)), train=False)
    assert "alpha_normal" in v["params"] and "alpha_reduce" in v["params"]
    assert v["params"]["alpha_normal"].shape == (num_edges(2), len(DEFAULT_OPS))
    out = net.apply(v, jnp.zeros((2, 8, 8, 3)), train=False)
    assert out.shape == (2, 5)


def test_derive_genotype_picks_strongest():
    E, O = num_edges(2), len(DEFAULT_OPS)
    alpha = np.zeros((E, O), np.float32)
    alpha[:, DEFAULT_OPS.index("sep_conv_3x3")] = 5.0  # dominate everywhere
    gene = derive_genotype(alpha, steps=2)
    assert len(gene) == 4  # 2 nodes x 2 kept edges
    assert all(op == "sep_conv_3x3" for op, _ in gene)
    # 'none' never selected even if strongest
    alpha2 = np.zeros((E, O), np.float32)
    alpha2[:, DEFAULT_OPS.index("none")] = 9.0
    alpha2[:, DEFAULT_OPS.index("skip_connect")] = 1.0
    gene2 = derive_genotype(alpha2, steps=2)
    assert all(op != "none" for op, _ in gene2)


def test_fednas_round_updates_alpha():
    from fedml_tpu.algorithms.fednas import FedNASAPI

    data = synthetic_classification(
        num_clients=3, num_classes=3, feat_shape=(8, 8, 3),
        samples_per_client=32, partition_method="homo", ragged=False, seed=1,
    )
    api = FedNASAPI(
        data, num_classes=3, input_shape=(8, 8, 3), ch=4, cells=1, steps=2,
        batch_size=8,
    )
    alpha_before = np.asarray(api.variables["params"]["alpha_normal"]).copy()
    geno = api.train_round(0, client_num_per_round=2, epochs=1)
    alpha_after = np.asarray(api.variables["params"]["alpha_normal"])
    assert not np.allclose(alpha_before, alpha_after)  # α actually searched
    assert len(geno) == 4
    assert len(api.genotype_history) == 1
    acc = api.evaluate(data.test_x, data.test_y)
    assert 0.0 <= acc <= 1.0
