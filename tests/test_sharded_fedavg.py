"""Sharded (multi-chip) FedAvg must match the single-chip vmap simulator.

The reference has no analog of this test — its distributed and standalone
paths are separate codebases that can drift. Here the distributed runtime is
the same round math sharded over a mesh, so we assert mesh-invariance: same
seeds => same global model whether the client axis lives on 1 device or 8
(up to fp32 reduction-order noise between tensordot and psum-of-partials)."""

import jax
import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, MeshConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.parallel import DistributedFedAvgAPI, make_mesh, pad_client_batch
from fedml_tpu.data.base import ClientBatch

NUM_CLIENTS = 12
NUM_CLASSES = 4
FEAT = (5,)


def _data():
    return synthetic_classification(
        num_clients=NUM_CLIENTS,
        num_classes=NUM_CLASSES,
        feat_shape=FEAT,
        samples_per_client=24,
        partition_method="hetero",
        partition_alpha=0.5,
        seed=7,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=NUM_CLASSES),
        input_shape=FEAT,
        num_classes=NUM_CLASSES,
        name="lr",
    )


def _config(per_round):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=per_round,
            comm_round=3,
            epochs=2,
            frequency_of_the_test=3,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1, momentum=0.9),
        seed=11,
    )


@pytest.mark.parametrize("per_round", [12, 10])  # 10 exercises dummy padding
def test_sharded_matches_single_chip(per_round):
    assert jax.device_count() >= 8, "conftest must force 8 virtual devices"
    data = _data()
    cfg = _config(per_round)

    single = FedAvgAPI(cfg, data, _model())
    single.train()

    mesh = make_mesh(8)
    dist = DistributedFedAvgAPI(cfg, data, _model(), mesh=mesh)
    dist.train()

    for a, b in zip(
        jax.tree_util.tree_leaves(single.global_vars),
        jax.tree_util.tree_leaves(dist.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        )


def test_pad_client_batch():
    b = ClientBatch(
        x=np.ones((5, 2, 3, 4), np.float32),
        y=np.ones((5, 2, 3), np.int32),
        mask=np.ones((5, 2, 3), np.float32),
        num_samples=np.ones((5,), np.float32),
    )
    p = pad_client_batch(b, 8)
    assert p.x.shape[0] == 8
    assert p.mask[5:].sum() == 0
    assert p.num_samples[5:].sum() == 0
    # already divisible: unchanged object
    assert pad_client_batch(p, 4) is p


def test_mesh_fedopt_matches_vmap_fedopt():
    """DistributedFedOptAPI (server optimizer over the mesh runtime) must
    reproduce the single-chip FedOptAPI: same seeds => same global params
    after several adam server steps."""
    from fedml_tpu.algorithms.fedopt import FedOptAPI
    from fedml_tpu.config import ServerConfig
    from fedml_tpu.parallel import DistributedFedOptAPI

    import dataclasses

    cfg = dataclasses.replace(
        _config(8),
        server=ServerConfig(server_optimizer="adam", server_lr=0.05),
    )
    ref = FedOptAPI(cfg, _data(), _model())
    mesh = make_mesh(4)
    dist = DistributedFedOptAPI(cfg, _data(), _model(), mesh=mesh)
    for r in range(cfg.fed.comm_round):
        ref.train_round(r)
        dist.train_round(r)
    ref_p = jax.tree_util.tree_leaves(ref.global_vars)
    dist_p = jax.tree_util.tree_leaves(dist.global_vars)
    for a, b in zip(ref_p, dist_p):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
        )
