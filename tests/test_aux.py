"""Aux subsystems: checkpoint round-trip, metrics logger summary (the CI
oracle surface), topology managers, robust aggregation, robust-FedAvg
no-defense equivalence, and the CLI end-to-end."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression


def _data(n=6):
    return synthetic_classification(
        num_clients=n, num_classes=4, feat_shape=(5,), samples_per_client=16,
        partition_method="homo", seed=2,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=4), input_shape=(5,), num_classes=4, name="lr"
    )


def test_checkpoint_roundtrip(tmp_path):
    from fedml_tpu.utils import load_checkpoint, restore_like, save_checkpoint

    params = {"params": {"dense": {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.zeros(3, np.float32)}}}
    opt = optax.adam(1e-3)
    opt_state = opt.init(params["params"])
    rng = jax.random.PRNGKey(7)
    p = str(tmp_path / "ckpt")
    algo_state = {"c": np.full((2,), 3.5, np.float32)}
    save_checkpoint(
        p, params, round_idx=5, rng=np.asarray(rng),
        server_opt_state=opt_state, algo_state=algo_state,
    )
    vars2, round_idx, rng2, opt2_raw, algo2, _ = load_checkpoint(p)
    assert round_idx == 5
    np.testing.assert_array_equal(algo2["c"], algo_state["c"])
    np.testing.assert_array_equal(np.asarray(rng), rng2)
    np.testing.assert_array_equal(
        vars2["params"]["dense"]["w"], params["params"]["dense"]["w"]
    )
    opt2 = restore_like(opt_state, opt2_raw)
    for a, b in zip(
        jax.tree_util.tree_leaves(opt_state), jax.tree_util.tree_leaves(opt2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_metrics_logger_summary(tmp_path):
    from fedml_tpu.utils import MetricsLogger

    with MetricsLogger(str(tmp_path)) as ml:
        ml.log({"round": 0, "Train/Acc": 0.5})
        ml.log({"round": 1, "Train/Acc": 0.7, "Test/Acc": 0.6})
    summary = json.loads((tmp_path / "summary.json").read_text())
    # wandb-summary.json semantics: last value per key (ref CI oracle,
    # CI-script-fedavg.sh:44)
    assert summary["Train/Acc"] == 0.7
    assert summary["round"] == 1
    lines = (tmp_path / "metrics.jsonl").read_text().strip().splitlines()
    assert len(lines) == 2


def test_symmetric_topology_rows_stochastic():
    from fedml_tpu.partition.topology import SymmetricTopologyManager

    t = SymmetricTopologyManager(8, neighbor_num=4)
    t.generate_topology()
    W = t.topology
    np.testing.assert_allclose(W.sum(axis=1), np.ones(8), atol=1e-6)
    assert (np.diag(W) > 0).all()
    # symmetric support
    assert ((W > 0) == (W.T > 0)).all()
    assert 1 in t.get_out_neighbor_idx_list(0)


def test_asymmetric_topology_rows_stochastic():
    from fedml_tpu.partition.topology import AsymmetricTopologyManager

    t = AsymmetricTopologyManager(8, undirected_neighbor_num=4, seed=1)
    t.generate_topology()
    np.testing.assert_allclose(t.topology.sum(axis=1), np.ones(8), atol=1e-6)


def test_norm_clip_tree():
    from fedml_tpu.robustness import norm_diff_clip_tree, tree_weight_norm

    g = {"params": {"w": jnp.zeros(4)}}
    l = {"params": {"w": jnp.full(4, 10.0)}}
    clipped = norm_diff_clip_tree(l, g, norm_bound=1.0)
    # diff norm 20 -> scaled to norm 1
    np.testing.assert_allclose(
        float(tree_weight_norm(clipped, g)), 1.0, rtol=1e-5
    )
    # under the bound: unchanged
    l2 = {"params": {"w": jnp.full(4, 0.1)}}
    c2 = norm_diff_clip_tree(l2, g, norm_bound=5.0)
    np.testing.assert_allclose(np.asarray(c2["params"]["w"]), 0.1, rtol=1e-6)


def test_robust_fedavg_no_defense_equals_fedavg():
    from fedml_tpu.algorithms import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_robust import RobustFedAvgAPI
    from fedml_tpu.robustness import RobustConfig

    data = _data()
    cfg = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(client_num_in_total=6, client_num_per_round=6, comm_round=2, epochs=1, frequency_of_the_test=2),
        train=TrainConfig(lr=0.1),
        seed=4,
    )
    plain = FedAvgAPI(cfg, data, _model())
    plain.train()
    # huge bound + no noise => identical to FedAvg
    rob = RobustFedAvgAPI(cfg, data, _model(), robust=RobustConfig(defense_type="norm_diff_clipping", norm_bound=1e9))
    rob.train()
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.global_vars),
        jax.tree_util.tree_leaves(rob.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_robust_fedavg_weak_dp_runs():
    from fedml_tpu.algorithms.fedavg_robust import RobustFedAvgAPI
    from fedml_tpu.robustness import RobustConfig

    data = _data()
    cfg = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(client_num_in_total=6, client_num_per_round=3, comm_round=2, epochs=1, frequency_of_the_test=2),
        train=TrainConfig(lr=0.1),
    )
    api = RobustFedAvgAPI(
        cfg, data, _model(), robust=RobustConfig(defense_type="weak_dp", norm_bound=5.0, stddev=0.01)
    )
    final = api.train()
    assert np.isfinite(final["Test/Loss"])


def test_cli_end_to_end(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli import main

    result = CliRunner().invoke(
        main,
        [
            "--dataset", "synthetic",
            "--model", "lr",
            "--client_num_in_total", "6",
            "--client_num_per_round", "3",
            "--comm_round", "2",
            "--batch_size", "8",
            "--lr", "0.1",
            "--log_dir", str(tmp_path / "logs"),
            "--checkpoint_path", str(tmp_path / "ckpt"),
        ],
    )
    assert result.exit_code == 0, result.output
    out = json.loads(result.output.strip().splitlines()[-1])
    assert "Test/Acc" in out
    assert (tmp_path / "logs" / "summary.json").exists()
    assert (tmp_path / "ckpt.npz").exists()


def test_cli_fedopt_and_hierarchical(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli import main

    for extra in (
        ["--algorithm", "fedopt", "--server_optimizer", "adam", "--server_lr", "0.05"],
        ["--algorithm", "hierarchical", "--group_num", "2"],
        ["--algorithm", "fedprox", "--prox_mu", "0.1"],
    ):
        result = CliRunner().invoke(
            main,
            [
                "--dataset", "synthetic", "--model", "lr",
                "--client_num_in_total", "4", "--client_num_per_round", "4",
                "--comm_round", "1", "--batch_size", "8",
            ]
            + extra,
        )
        assert result.exit_code == 0, result.output
