"""The benchmark's one-shot record must survive pathology: budget
exhaustion and failing sections degrade to self-describing rows, never to
a missing or unparseable record (the driver runs bench.py exactly once
per round — a lost record loses the round's perf evidence)."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_exhausted_budget_still_emits_one_json_record():
    """FEDML_TPU_BENCH_BUDGET_S=1: every section (including the mandatory
    throughput rows, which carry min_remaining_s=0 but are budget-gated
    like the rest) skips, and the script still prints exactly one JSON
    line with value=None, the error marker, and a skip reason per
    section."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # inherited by the backend-alive probe
    env["FEDML_TPU_BENCH_BUDGET_S"] = "1"
    out = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, out.stdout[-2000:]
    rec = json.loads(lines[0])
    assert rec["metric"] == "femnist_cnn_fedavg_rounds_per_sec"
    assert rec["value"] is None
    assert rec["error"] == "all throughput sections failed"
    # the degraded record still carries every section slot, each naming why
    for key in ("north_star", "bf16_cross_silo_resnet56", "mxu_validation",
                "scale_100k_clients"):
        assert "skipped" in rec[key], key
    for row in rec["hard_accuracy"]["synthetic11"]:
        assert "skipped" in row
    # no fabricated measurement claims in a record with no measurements
    assert rec["fused_note"] is None
    assert rec["fused_vs_eager_trainloop"] is None
