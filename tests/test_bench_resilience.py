"""The benchmark's record must survive pathology — round 4 lost its ENTIRE
perf record when the driver's timeout killed bench.py before its single
end-of-run print (BENCH_r04.json: rc=124, parsed=null). The r5 design is
pinned here: a compact (<1800 char) record line is flushed to stdout after
EVERY section and the full detail file is atomically rewritten alongside,
so no kill — budget gate, SIGTERM, watchdog, or raw SIGKILL — can erase
completed sections. The driver parses the LAST LINE of a ~2000-char output
tail; these tests parse the same way."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(budget, tiny=None, sleep=None, detail=None, wd_frac=None,
         sleep_only=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # inherited by the backend-alive probe
    env["FEDML_TPU_BENCH_BUDGET_S"] = str(budget)
    if tiny:
        env["FEDML_TPU_BENCH_TINY"] = "1"
    if sleep is not None:
        env["FEDML_TPU_BENCH_TINY_SLEEP"] = str(sleep)
    if detail:
        env["FEDML_TPU_BENCH_DETAIL"] = detail
    if wd_frac is not None:
        env["FEDML_TPU_BENCH_WATCHDOG_FRAC"] = str(wd_frac)
    if sleep_only:
        env["FEDML_TPU_BENCH_TINY_SLEEP_ONLY"] = "1"
    return env


def _last_record(stdout: str) -> dict:
    """Parse exactly the way the driver does: last line of the tail."""
    lines = [l for l in stdout.strip().splitlines() if l.startswith("{")]
    assert lines, stdout[-2000:]
    assert len(lines[-1]) < 1800, "compact line must fit the driver's tail"
    return json.loads(lines[-1])


@pytest.mark.slow
def test_bench_exhausted_budget_still_emits_parseable_record(tmp_path):
    """FEDML_TPU_BENCH_BUDGET_S=1: every section (including the mandatory
    throughput rows) skips via the budget gate, and the LAST stdout line
    is still a parseable compact record naming every skip."""
    detail = str(tmp_path / "detail.json")
    out = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True, text=True, timeout=300,
        # wd_frac=200 keeps the watchdog (budget*200 = 200 s) out of this
        # test's way: the subject is the per-section budget gate
        env=_env(budget=1, detail=detail, wd_frac=200), cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = _last_record(out.stdout)
    assert rec["metric"] == "femnist_cnn_fedavg_rounds_per_sec"
    assert rec["value"] is None
    assert rec["error"] == "all throughput sections failed"
    assert rec["partial"] is False
    assert rec["expected_deviations"] == []  # skips are not deviations
    for k, v in rec["sections"].items():
        assert v.startswith("skip:"), (k, v)
    # the detail file carries the same degraded evidence, with no
    # fabricated measurement claims
    det = json.load(open(detail))
    assert det.get("fused_note") is None
    assert det.get("fused_vs_eager_trainloop") is None
    for row in det["hard_accuracy"]["synthetic11"]:
        assert "skipped" in row


@pytest.mark.slow
def test_bench_survives_sigkill_mid_run(tmp_path):
    """THE round-4 failure mode, pinned (VERDICT r4 Next #1): kill -9 the
    bench mid-flight; everything completed before the kill must already
    be on stdout (compact line) and in the detail file."""
    detail = str(tmp_path / "detail.json")
    p = subprocess.Popen(
        [sys.executable, "bench.py"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=_env(budget=3600, tiny=True, sleep=600, detail=detail), cwd=REPO,
    )
    lines = []
    try:
        deadline = time.time() + 280
        while time.time() < deadline:
            line = p.stdout.readline()
            if not line:
                break
            lines.append(line)
            rec = json.loads(line)
            if "r/s" in rec["sections"]["north_star"]:
                break  # first real section completed & flushed
        else:
            pytest.fail("north_star section never completed")
        p.kill()  # SIGKILL — no handler can run
    finally:
        if p.poll() is None:
            p.kill()
        p.wait()
    assert lines, "no incremental emission before the kill"
    rec = json.loads(lines[-1])
    assert "r/s" in rec["sections"]["north_star"]
    assert rec["value"] is not None  # headline already assembled
    det = json.load(open(detail))
    assert "rounds_per_sec" in det["north_star"]


@pytest.mark.slow
def test_bench_sigterm_finalizes_record(tmp_path):
    """The driver's `timeout` sends SIGTERM before SIGKILL — the handler
    must finalize and exit promptly with the record as the last line."""
    detail = str(tmp_path / "detail.json")
    p = subprocess.Popen(
        [sys.executable, "bench.py"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=_env(budget=3600, tiny=True, sleep=600, detail=detail), cwd=REPO,
    )
    try:
        time.sleep(12)  # mid-probe / early first section
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    rec = _last_record(out)
    assert rec["partial"] is True
    assert "SIGTERM" in rec.get("finalize_note", "")


@pytest.mark.slow
def test_bench_watchdog_fires_before_driver_timeout(tmp_path):
    """A section that hangs past the whole budget cannot take the record
    with it: the watchdog thread finalizes at 92% of the budget and
    os._exit's — even though the main thread is still asleep."""
    detail = str(tmp_path / "detail.json")
    t0 = time.time()
    # budget 120: the section gate admits the sleeper (start_deadline =
    # 0.92*120-60 = 50s > probe time) and the watchdog fires at 110s,
    # mid-sleep — the exact hang-past-the-budget scenario
    out = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True, text=True, timeout=280,
        env=_env(budget=120, tiny=True, sleep=600, detail=detail,
                 sleep_only=True), cwd=REPO,
    )
    # exited on its own (well before the sleeper's 600 s), record intact
    assert time.time() - t0 < 240
    rec = _last_record(out.stdout)
    assert rec["partial"] is True
    assert "watchdog" in rec.get("finalize_note", "")
