"""Loaders vs COMMITTED golden fixtures (VERDICT r2 Weak #9/Next #10): the
fixtures in tests/golden/ are one-client byte-level files built to the real
formats' published specs (leaf benchmark JSON layout, TFF federated-EMNIST
h5 group structure, GLD-23k mapping CSV) — independent artifacts, not
files the loader tests synthesized from the loader's own assumptions."""

import os

import numpy as np

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def test_leaf_golden_json():
    from fedml_tpu.data.leaf import load_femnist_leaf

    ds = load_femnist_leaf(os.path.join(GOLDEN, "leaf_femnist"))
    assert ds.num_clients == 1
    assert ds.client_x[0].shape == (3, 28, 28, 1)
    assert ds.client_y[0].dtype == np.int32
    assert ds.client_test_x[0].shape[0] == 2
    assert 0.0 <= ds.client_x[0].min() and ds.client_x[0].max() <= 1.0
    assert ds.num_classes == 62


def test_tff_h5_golden():
    import shutil
    import tempfile

    from fedml_tpu.data import tff_h5

    with tempfile.TemporaryDirectory() as d:
        shutil.copy(
            os.path.join(GOLDEN, "fed_emnist_train.h5"),
            os.path.join(d, tff_h5.FEMNIST_TRAIN),
        )
        shutil.copy(
            os.path.join(GOLDEN, "fed_emnist_test.h5"),
            os.path.join(d, tff_h5.FEMNIST_TEST),
        )
        ds = tff_h5.load_femnist(d)
    assert ds.num_clients == 1
    assert ds.client_x[0].shape == (4, 28, 28, 1)
    assert ds.client_x[0].dtype == np.float32
    assert ds.test_x.shape[0] == 2


def test_landmarks_golden_csv():
    from fedml_tpu.data.landmarks import load_landmarks

    ds = load_landmarks(
        os.path.join(GOLDEN, "landmarks"),
        train_map_file="federated_train.csv",
        test_map_file="test.csv",
        image_size=8,
    )
    assert ds.num_clients == 1
    assert ds.client_x[0].shape == (2, 8, 8, 3)
    # class ids are densified to 0..K-1 (consistently across splits): the
    # test image is class "5", same as train image golden_img_a
    assert sorted(ds.client_y[0].tolist()) == [0, 1]
    assert ds.test_x.shape == (1, 8, 8, 3)
    a_label = ds.client_y[0][0]  # golden_img_a, class "5"
    assert ds.test_y[0] == a_label
