"""Round-2 data loaders (VERDICT r1 missing #3): ImageNet, Landmarks, UCI
streaming, NUS-WIDE + Lending Club vertical. Each gets a tiny fixture in the
real on-disk format, same pattern as tests/test_data_loaders.py."""

import csv
import os

import numpy as np
import pytest


def _png(path, size=8, seed=0):
    from PIL import Image

    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 255, size=(size, size, 3), dtype=np.uint8)
    Image.fromarray(arr).save(path)


# --- ImageNet -------------------------------------------------------------


def _write_imagenet(root, n_classes=3, per_class=4, size=8):
    for split in ("train", "val"):
        for c in range(n_classes):
            d = os.path.join(root, split, f"n{c:08d}")
            os.makedirs(d, exist_ok=True)
            n = per_class if split == "train" else 2
            for i in range(n):
                _png(os.path.join(d, f"img_{i}.png"), size=size, seed=c * 100 + i)


def test_imagenet_loader(tmp_path):
    from fedml_tpu.data.imagenet import load_imagenet

    _write_imagenet(str(tmp_path))
    data = load_imagenet(str(tmp_path), num_clients=3, image_size=8)
    assert data.num_clients == 3
    assert data.num_classes == 3
    assert sum(len(y) for y in data.client_y) == 12
    assert data.client_x[0].shape[1:] == (8, 8, 3)
    assert len(data.test_y) == 6
    # normalized with ImageNet stats: roughly centered
    assert abs(float(np.mean(data.test_x))) < 3.0


def test_imagenet_lda_partition(tmp_path):
    from fedml_tpu.data.imagenet import load_imagenet

    _write_imagenet(str(tmp_path), per_class=8)
    data = load_imagenet(
        str(tmp_path), num_clients=4, image_size=8,
        partition_method="hetero", partition_alpha=0.2,
    )
    sizes = [len(y) for y in data.client_y]
    assert sum(sizes) == 24 and data.num_clients == 4


def test_imagenet_registry(tmp_path):
    from fedml_tpu.config import DataConfig, FedConfig, RunConfig
    from fedml_tpu.data import registry

    _write_imagenet(str(tmp_path))
    cfg = RunConfig(
        data=DataConfig(dataset="imagenet", data_dir=str(tmp_path)),
        fed=FedConfig(client_num_in_total=3),
    )
    # registry path: image_size default 224 would blow up 8x8 fixtures;
    # loader signature keeps data_dir first so direct use covers that —
    # registry smoke just confirms dispatch works
    data = registry.load(cfg)
    assert data.name == "imagenet"


# --- Landmarks ------------------------------------------------------------


def _write_landmarks(root, users=3, per_user=3, n_classes=2):
    img_dir = os.path.join(root, "images")
    os.makedirs(img_dir, exist_ok=True)
    rows = []
    k = 0
    for u in range(users):
        for i in range(per_user):
            iid = f"im{k:04d}"
            _png(os.path.join(img_dir, iid + ".png"), size=8, seed=k)
            rows.append({"user_id": str(u), "image_id": iid, "class": f"c{k % n_classes}"})
            k += 1
    with open(os.path.join(root, "mini_gld_train_split.csv"), "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["user_id", "image_id", "class"])
        w.writeheader()
        w.writerows(rows)
    test_rows = []
    for i in range(3):
        iid = f"te{i:04d}"
        _png(os.path.join(img_dir, iid + ".png"), size=8, seed=1000 + i)
        test_rows.append({"image_id": iid, "class": f"c{i % n_classes}"})
    with open(os.path.join(root, "mini_gld_test.csv"), "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["image_id", "class"])
        w.writeheader()
        w.writerows(test_rows)


def test_landmarks_loader(tmp_path):
    from fedml_tpu.data.landmarks import load_landmarks

    _write_landmarks(str(tmp_path))
    data = load_landmarks(str(tmp_path), image_size=8)
    assert data.num_clients == 3  # one shard per user_id: natural federation
    assert all(len(y) == 3 for y in data.client_y)
    assert data.num_classes == 2
    assert data.test_x.shape == (3, 8, 8, 3)


def test_landmarks_bad_mapping_raises(tmp_path):
    from fedml_tpu.data.landmarks import load_landmarks

    os.makedirs(tmp_path / "images", exist_ok=True)
    with open(tmp_path / "mini_gld_train_split.csv", "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["wrong", "cols"])
        w.writeheader()
        w.writerow({"wrong": "1", "cols": "2"})
    with open(tmp_path / "mini_gld_test.csv", "w") as f:
        f.write("image_id,class\n")
    with pytest.raises(ValueError, match="image_id and class"):
        load_landmarks(str(tmp_path), image_size=8)


# --- UCI streaming --------------------------------------------------------


def _write_susy(path, n=200, d=4, seed=3):
    rng = np.random.default_rng(seed)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        for _ in range(n):
            y = rng.integers(0, 2)
            # two feature regimes so k-means has something to find
            x = rng.normal(3.0 * y, 1.0, size=d)
            w.writerow([float(y)] + [round(float(v), 4) for v in x])


def test_uci_streaming_shapes_and_regimes(tmp_path):
    from fedml_tpu.data.uci import load_uci_streaming

    p = str(tmp_path / "susy.csv")
    _write_susy(p)
    xs, ys = load_uci_streaming(p, num_clients=4, samples_per_client=20, beta=0.5)
    assert xs.shape == (4, 20, 4) and ys.shape == (4, 20)
    assert set(np.unique(ys)) <= {0, 1}


def test_uci_streaming_feeds_decentralized(tmp_path):
    from fedml_tpu.algorithms.decentralized import DecentralizedAPI
    from fedml_tpu.data.uci import load_uci_streaming
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression
    from fedml_tpu.partition.topology import SymmetricTopologyManager

    p = str(tmp_path / "susy.csv")
    _write_susy(p)
    xs, ys = load_uci_streaming(p, num_clients=4, samples_per_client=30, beta=0.3)
    topo = SymmetricTopologyManager(4, neighbor_num=2)
    topo.generate_topology()
    model = ModelDef(LogisticRegression(num_classes=1), (4,), 1, name="lr")
    api = DecentralizedAPI(model, topo, lr=0.2, variant="dsgd")
    out = api.run(xs, ys.astype(np.float32))
    assert np.isfinite(out["regret"]).all()
    # separable regimes: online loss should drop
    assert out["regret"][-1] < out["regret"][2]


def test_uci_insufficient_samples_raises(tmp_path):
    from fedml_tpu.data.uci import load_uci_streaming

    p = str(tmp_path / "susy.csv")
    _write_susy(p, n=10)
    with pytest.raises(ValueError, match="need"):
        load_uci_streaming(p, num_clients=4, samples_per_client=20)


# --- NUS-WIDE -------------------------------------------------------------


def _write_nus(root, labels=("grass", "water"), n=24, d_feat=6, d_tags=8, seed=5):
    rng = np.random.default_rng(seed)
    for dtype, nn in (("Train", n), ("Test", max(8, n // 3))):
        lab_dir = os.path.join(root, "Groundtruth", "TrainTestLabels")
        os.makedirs(lab_dir, exist_ok=True)
        which = rng.integers(0, len(labels), size=nn)
        for li, lab in enumerate(labels):
            col = (which == li).astype(int)
            with open(os.path.join(lab_dir, f"Labels_{lab}_{dtype}.txt"), "w") as f:
                f.write("\n".join(str(v) for v in col))
        feat_dir = os.path.join(root, "Low_Level_Features")
        os.makedirs(feat_dir, exist_ok=True)
        feats = rng.normal(which[:, None], 0.3, size=(nn, d_feat))
        with open(os.path.join(feat_dir, f"{dtype}_Normalized_CH.dat"), "w") as f:
            for row in feats:
                f.write(" ".join(f"{v:.4f}" for v in row) + " \n")
        tag_dir = os.path.join(root, "NUS_WID_Tags")
        os.makedirs(tag_dir, exist_ok=True)
        tags = rng.integers(0, 2, size=(nn, d_tags))
        with open(os.path.join(tag_dir, f"{dtype}_Tags1k.dat"), "w") as f:
            for row in tags:
                f.write("\t".join(str(v) for v in row) + "\n")


def test_nus_wide_two_and_three_party(tmp_path):
    from fedml_tpu.data.vertical import load_nus_wide

    _write_nus(str(tmp_path))
    data2 = load_nus_wide(str(tmp_path), selected_labels=("grass", "water"), parties=2)
    assert len(data2.train_xs) == 2
    assert data2.train_xs[0].shape[1] == 6 and data2.train_xs[1].shape[1] == 8
    assert data2.train_xs[0].shape[0] == len(data2.train_y)
    assert set(np.unique(data2.train_y)) <= {0.0, 1.0}

    data3 = load_nus_wide(str(tmp_path), selected_labels=("grass", "water"), parties=3)
    assert len(data3.train_xs) == 3
    assert data3.train_xs[1].shape[1] + data3.train_xs[2].shape[1] == 8


def test_nus_wide_vfl_learns(tmp_path):
    from fedml_tpu.data.vertical import load_nus_wide, run_vfl

    _write_nus(str(tmp_path), n=64)
    data = load_nus_wide(str(tmp_path), selected_labels=("grass", "water"))
    _, stats = run_vfl(data, epochs=15, lr=0.1, batch_size=16)
    assert stats["acc"] > 0.8  # party A's features carry the label signal


# --- Lending Club ---------------------------------------------------------


def _write_lending_club(path, n=60, seed=6):
    rng = np.random.default_rng(seed)
    cols = [
        "annual_inc", "emp_length", "home_ownership", "verification_status",
        "grade", "loan_amnt", "int_rate", "installment", "term", "purpose",
        "dti", "total_pymnt", "total_rec_int", "total_rec_prncp",
        "last_pymnt_amnt", "loan_status",
    ]
    grades = list("ABCDEFG")
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        for _ in range(n):
            bad = rng.random() < 0.4
            w.writerow({
                "annual_inc": round(float(rng.uniform(2e4, 2e5)), 2),
                "emp_length": rng.choice(["< 1 year", "5 years", "10+ years", ""]),
                "home_ownership": rng.choice(["RENT", "OWN", "MORTGAGE"]),
                "verification_status": rng.choice(["Verified", "Not Verified"]),
                "grade": grades[int(rng.integers(0, 7))],
                "loan_amnt": round(float(rng.uniform(1e3, 4e4)), 2),
                "int_rate": round(float(rng.uniform(5, 30)), 2),
                "installment": round(float(rng.uniform(30, 1500)), 2),
                "term": " 36 months",
                "purpose": rng.choice(["credit_card", "car", "small_business"]),
                "dti": round(float(rng.uniform(0, 40)), 2),
                "total_pymnt": round(float(rng.uniform(0, 5e4)), 2),
                "total_rec_int": round(float(rng.uniform(0, 1e4)), 2),
                "total_rec_prncp": round(float(rng.uniform(0, 4e4)), 2),
                "last_pymnt_amnt": round(float(rng.uniform(0, 2e3)), 2),
                "loan_status": "Charged Off" if bad else "Fully Paid",
            })


def test_lending_club_three_party_split(tmp_path):
    from fedml_tpu.data.vertical import (
        QUALIFICATION_FEATURES, LOAN_FEATURES, REPAYMENT_FEATURES,
        load_lending_club,
    )

    p = str(tmp_path / "loans.csv")
    _write_lending_club(p)
    data = load_lending_club(p)
    assert [x.shape[1] for x in data.train_xs] == [
        len(QUALIFICATION_FEATURES), len(LOAN_FEATURES), len(REPAYMENT_FEATURES)
    ]
    assert len(data.train_y) + len(data.test_y) == 60
    assert 0.0 < float(data.train_y.mean()) < 1.0  # both classes present
    # z-scored features
    assert abs(float(data.train_xs[0].mean())) < 0.5


def test_lending_club_vfl_runs(tmp_path):
    from fedml_tpu.data.vertical import load_lending_club, run_vfl

    p = str(tmp_path / "loans.csv")
    _write_lending_club(p, n=80)
    data = load_lending_club(p)
    _, stats = run_vfl(data, epochs=5, lr=0.05, batch_size=16)
    assert np.isfinite(stats["loss"])


def test_synthetic_shakespeare_geometry():
    """shakespeare_synth: leaf-shakespeare shapes (80-char int windows,
    vocab 90), ragged shards, deterministic under seed, and the y label is
    the chain's next char (x windows stride by one)."""
    from fedml_tpu.data.synthetic import synthetic_shakespeare

    d1 = synthetic_shakespeare(num_clients=6, samples_per_client=20, seed=3)
    d2 = synthetic_shakespeare(num_clients=6, samples_per_client=20, seed=3)
    assert d1.num_clients == 6
    sizes = {len(y) for y in d1.client_y}
    assert len(sizes) > 1  # ragged
    for cx, cy in zip(d1.client_x, d1.client_y):
        assert cx.shape[1:] == (80,) and cx.dtype == np.int32
        assert cx.min() >= 0 and cx.max() < 90
        assert cy.min() >= 0 and cy.max() < 90
        # windows stride one char over one chain: next window starts with
        # this window shifted left, and y is the char that completes it
        np.testing.assert_array_equal(cx[1, :-1], cx[0, 1:])
        assert cy[0] == cx[1, -1]
    np.testing.assert_array_equal(d1.client_x[0], d2.client_x[0])
    np.testing.assert_array_equal(d1.test_y, d2.test_y)
