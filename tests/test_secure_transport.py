"""Secure aggregation in the transport round loop (ref distributed
turboaggregate): masked uploads, exact-weighted-average reconstruction,
and dropout mask recovery on the quorum path."""

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
from fedml_tpu.config import (
    CommConfig,
    DataConfig,
    FedConfig,
    RunConfig,
    TrainConfig,
)
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.secagg.secure_aggregation import (
    flatten_tree,
    mask_round_update,
    round_aggregator,
    unflatten_like,
    unmask_round_average,
)


def _fixture(secure):
    data = synthetic_classification(
        num_clients=4, num_classes=3, feat_shape=(5,), samples_per_client=12,
        partition_method="homo", seed=9,
    )
    model_def = lambda: ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=3,
            epochs=1, frequency_of_the_test=3,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        comm=CommConfig(secure_agg=secure),
        seed=0,
    )
    return cfg, data, model_def


def test_secure_loopback_matches_plain():
    """The server never sees a raw update, yet the trained model equals the
    plain transport run up to the 2^-16 fixed-point grid."""
    from fedml_tpu.algorithms import FedAvgAPI

    cfg, data, model_def = _fixture(secure=True)
    sim = FedAvgAPI(cfg.replace(comm=CommConfig()), data, model_def())
    sim.train()
    server = run_loopback_federation(cfg, data, model_def())
    assert server.round_idx == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(server.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3
        )


def test_secure_round_dropout_recovery():
    """A party that vanishes AFTER masking: survivors' masks toward it are
    unwound and the result is exactly the survivors' weighted average."""
    rng = np.random.default_rng(0)
    w_round = {"w": rng.normal(size=(6, 3)).astype(np.float32),
               "b": rng.normal(size=(3,)).astype(np.float32)}
    locals_ = [
        jax.tree_util.tree_map(
            lambda a, s=s: a + rng.normal(scale=0.01, size=a.shape).astype(a.dtype),
            w_round,
        )
        for s in range(4)
    ]
    ns = {0: 10.0, 1: 20.0, 2: 30.0, 3: 40.0}
    dim = sum(a.size for a in jax.tree_util.tree_leaves(w_round))
    agg = round_aggregator(4, dim, seed=3, round_idx=5)
    uploads = {
        i: mask_round_update(agg, i, locals_[i], w_round, ns[i])
        for i in range(4)
    }
    uploads.pop(2)  # party 2 drops after masking
    got = unmask_round_average(agg, uploads, ns, w_round)
    # expected: weighted average over survivors only
    flat_round, spec = flatten_tree(w_round)
    num = np.zeros_like(flat_round)
    for i in (0, 1, 3):
        fl, _ = flatten_tree(locals_[i])
        num += ns[i] * (fl - flat_round)
    expect = unflatten_like(spec, flat_round + num / (10 + 20 + 40))
    for k in w_round:
        np.testing.assert_allclose(got[k], expect[k], atol=5e-4)


def test_masked_upload_hides_update():
    """A single masked upload is statistically unrelated to the raw update
    (the mask is a full-range field element per coordinate)."""
    w_round = {"w": np.zeros((4, 4), np.float32)}
    w_local = {"w": np.full((4, 4), 0.01, np.float32)}
    agg = round_aggregator(3, 16, seed=1, round_idx=0)
    masked = mask_round_update(agg, 0, w_local, w_round, 5.0)
    from fedml_tpu.secagg.secure_aggregation import encode_fixed

    raw = encode_fixed(5.0 * 0.01 * np.ones(16))
    # masked differs from raw in (essentially) every coordinate
    assert np.mean(masked == raw) < 0.2


def test_mask_round_update_rejects_field_overflow():
    """Magnitudes that would wrap the fixed-point field raise at encode
    instead of silently corrupting the aggregate."""
    import pytest

    w_round = {"w": np.zeros((4,), np.float32)}
    w_local = {"w": np.full((4,), 10.0, np.float32)}
    agg = round_aggregator(4, 4, seed=0, round_idx=0)
    with pytest.raises(ValueError, match="field bound"):
        mask_round_update(agg, 0, w_local, w_round, 10_000.0)
    # in-range magnitudes pass
    mask_round_update(agg, 0, w_local, w_round, 12.0)


def test_dh_group_and_secret_space():
    """VERDICT r3 Weak #5 closed: the key agreement is a 2048-bit MODP
    group (RFC 3526 group 14) with >= 128-bit secret space — nothing
    about the masks is brute-forceable."""
    from fedml_tpu.secagg import mpc

    p = mpc.MODP_2048_P
    assert p.bit_length() == 2048 and p % 2 == 1
    # RFC 3526 structure: top and bottom 64 bits are all-ones
    assert p >> (2048 - 64) == (1 << 64) - 1
    assert p & ((1 << 64) - 1) == (1 << 64) - 1
    # Fermat base-2 — catches any transcription error in the constant
    assert pow(2, p - 1, p) == 1
    # safe prime: q = (p-1)/2 is also prime (Miller-Rabin, fixed bases)
    q = (p - 1) // 2
    d, r = q - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for a in (2, 3, 5, 7, 11, 13, 17):
        x = pow(a, d, q)
        if x in (1, q - 1):
            continue
        for _ in range(r - 1):
            x = pow(x, 2, q)
            if x == q - 1:
                break
        else:
            raise AssertionError(f"(p-1)/2 failed Miller-Rabin base {a}")

    assert mpc.DH_SECRET_BITS >= 128
    sk = mpc.dh_secret()
    # the top bit is pinned: secret space is exactly 2^255
    assert 1 << (mpc.DH_SECRET_BITS - 1) <= sk < 1 << mpc.DH_SECRET_BITS
    assert mpc.dh_secret() != mpc.dh_secret()  # OS entropy, not a constant

    # key agreement symmetry + degenerate-pk rejection
    a, b = mpc.dh_secret(), mpc.dh_secret()
    assert mpc.dh_shared(a, mpc.dh_public(b)) == mpc.dh_shared(b, mpc.dh_public(a))
    import pytest

    for bad in (0, 1, p - 1, p, p + 1):
        with pytest.raises(ValueError):
            mpc.dh_shared(a, bad)


def test_pair_mask_kdf_properties():
    """Mask expansion: deterministic per (key, pair), distinct across
    pairs and keys, full-field-range uniform-ish."""
    from fedml_tpu.secagg import mpc
    from fedml_tpu.secagg.mpc import FIELD_PRIME

    k1 = mpc.dh_shared(mpc.dh_secret(), mpc.dh_public(mpc.dh_secret()))
    m = mpc.derive_pair_mask(k1, 0, 1, 4096)
    np.testing.assert_array_equal(m, mpc.derive_pair_mask(k1, 0, 1, 4096))
    assert np.any(m != mpc.derive_pair_mask(k1, 0, 2, 4096))
    assert np.any(m != mpc.derive_pair_mask(k1 + 1, 0, 1, 4096))
    assert np.all((0 <= m) & (m < FIELD_PRIME))
    # rough uniformity: mean of U[0, p) is p/2 within a few stddevs
    assert abs(m.mean() / FIELD_PRIME - 0.5) < 0.05


def _party_exchange(n_parties, dim, rngs=None):
    """Full client-held-key exchange: parties generate local keypairs, the
    'server' relays the pk registry (public material only)."""
    from fedml_tpu.secagg.secure_aggregation import ClientParty

    parties = [
        ClientParty(i, dim, rng=(rngs[i] if rngs else None))
        for i in range(n_parties)
    ]
    registry = {p.party: p.pk for p in parties}
    for p in parties:
        p.set_registry(registry)
    return parties


def test_client_held_keys_not_derivable_from_config_seed():
    """VERDICT r2 Weak #4: round 2 derived all secret keys from
    config.seed, so the server could recompute every mask. Now two
    executions of the SAME configured round produce different masks
    (client-local entropy), while both decode to the same average."""
    from fedml_tpu.secagg.secure_aggregation import ServerAggregator

    w_round = {"w": np.zeros((8,), np.float32)}
    w_local = {"w": np.full((8,), 0.02, np.float32)}
    uploads = []
    for _ in range(2):
        parties = _party_exchange(3, 8)
        uploads.append(
            {p.party: p.masked_update(w_local, w_round, 4.0) for p in parties}
        )
    # masks differ run to run — nothing about them is derivable from any
    # shared configuration
    assert np.mean(uploads[0][0] == uploads[1][0]) < 0.2
    srv = ServerAggregator(8)
    for up in uploads:
        avg = srv.decode_average(
            srv.masked_sum(up), {0: 4.0, 1: 4.0, 2: 4.0}, w_round
        )
        np.testing.assert_allclose(avg["w"], 0.02, atol=5e-4)


def test_server_cannot_reconstruct_individual_update():
    """Give the server EVERYTHING it observes in a dropout-free round —
    the pk registry and every masked upload — and check an individual
    update is not recoverable while the sum is exact."""
    from fedml_tpu.secagg.secure_aggregation import (
        ServerAggregator,
        decode_fixed,
        encode_fixed,
    )

    dim = 16
    rng = np.random.default_rng(7)
    w_round = {"w": np.zeros((dim,), np.float32)}
    locals_ = [
        {"w": rng.normal(scale=0.01, size=(dim,)).astype(np.float32)}
        for _ in range(4)
    ]
    parties = _party_exchange(4, dim)
    ns = {i: 1.0 for i in range(4)}
    uploads = {
        p.party: p.masked_update(locals_[p.party], w_round, 1.0)
        for p in parties
    }
    srv = ServerAggregator(dim)
    # the sum is exact (fixed-point grid)
    avg = srv.decode_average(srv.masked_sum(uploads), ns, w_round)
    expect = np.mean([l["w"] for l in locals_], axis=0)
    np.testing.assert_allclose(avg["w"], expect, atol=5e-4)
    # ...but any single observed upload decodes to mask noise, nowhere
    # near the raw update: the best the server can do with its observations
    # is the sum. (The true update is ~0.01-scale; the masked decode is
    # uniform over the +-16k fixed-point range.)
    for i in range(4):
        single = decode_fixed(uploads[i], 1)
        err = np.abs(single - locals_[i]["w"])
        assert np.median(err) > 1.0, "masked upload leaked the raw update"
    # and the server object itself never held a secret
    assert not hasattr(srv, "sks") and not hasattr(srv, "pair_keys")


def test_client_party_dropout_recovery_exchange():
    """Registry party drops before uploading: survivors' recovery masks
    restore the survivors-only weighted average."""
    from fedml_tpu.secagg.secure_aggregation import ServerAggregator

    dim = 12
    rng = np.random.default_rng(3)
    w_round = {"w": rng.normal(size=(dim,)).astype(np.float32)}
    locals_ = [
        jax.tree_util.tree_map(
            lambda a: a + rng.normal(scale=0.01, size=a.shape).astype(a.dtype),
            w_round,
        )
        for _ in range(4)
    ]
    ns = {0: 10.0, 1: 20.0, 3: 40.0}
    parties = _party_exchange(4, dim)
    uploads = {
        i: parties[i].masked_update(locals_[i], w_round, n)
        for i, n in ns.items()
    }  # party 2 never uploads
    recovery = {i: parties[i].recovery_mask([2]) for i in uploads}
    srv = ServerAggregator(dim)
    total = srv.remove_dropout_masks(srv.masked_sum(uploads), recovery)
    got = srv.decode_average(total, ns, w_round)
    num = np.zeros(dim)
    for i, n in ns.items():
        num += n * (locals_[i]["w"] - w_round["w"])
    expect = w_round["w"] + num / sum(ns.values())
    np.testing.assert_allclose(got["w"], expect, atol=5e-4)


def test_secure_quorum_deadline_recovers_dropout():
    """End-to-end: a deadline quorum round with a straggler exercises the
    recovery path inside the server FSM (finite, reasonable model out)."""
    import fedml_tpu.algorithms.fedavg_transport as T

    cfg, data, model_def = _fixture(secure=True)
    # straggler delay (1.8s) > deadline (1.0s) but < 2 rounds' deadlines:
    # its round-r upload lands while round r+1 is still open, so the
    # server is alive to count the drop
    cfg = cfg.replace(
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=3,
            epochs=1, frequency_of_the_test=3, deadline_s=1.0, min_clients=2,
        )
    )
    orig_train = T.LocalTrainer.train

    def slow_train(self, round_idx, variables):
        if self.client_index == 3:  # one straggler every round
            import time

            time.sleep(1.8)
        return orig_train(self, round_idx, variables)

    T.LocalTrainer.train = slow_train
    try:
        server = run_loopback_federation(cfg, data, model_def())
    finally:
        T.LocalTrainer.train = orig_train
    assert server.round_idx == 3
    assert server.dropped_uploads >= 1  # the straggler was dropped
    assert np.isfinite(server.history[-1]["Test/Loss"])


def test_secure_client_dead_before_pubkey_completes_on_quorum():
    """A client that dies BEFORE advertising its round key must not
    deadlock the key phase: after the deadline the server broadcasts the
    registry of parties heard so far and the round completes on quorum."""
    import fedml_tpu.algorithms.fedavg_transport as T

    cfg, data, model_def = _fixture(secure=True)
    cfg = cfg.replace(
        fed=FedConfig(
            client_num_in_total=4, client_num_per_round=4, comm_round=2,
            epochs=1, frequency_of_the_test=2, deadline_s=1.0, min_clients=2,
        )
    )
    orig = T.FedAvgClientManager._on_sync

    def dying_on_sync(self, msg):
        # rank 4 "dies" (stops responding entirely) from round 1 on
        if self.rank == 4 and msg.get("round_idx") >= 1:
            return
        return orig(self, msg)

    T.FedAvgClientManager._on_sync = dying_on_sync
    try:
        server = run_loopback_federation(cfg, data, model_def())
    finally:
        T.FedAvgClientManager._on_sync = orig
    assert server.round_idx == 2
    assert np.isfinite(server.history[-1]["Test/Loss"])
