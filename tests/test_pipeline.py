"""Round pipeline (FedConfig.pipeline): preparing round r+1's host work
while round r's device dispatch is in flight must be byte-identical to the
serial loop — the stash commit point is the same `_warm_placed` contract
warmup uses — and must degrade to serial automatically whenever next
round's inputs depend on this round's outcome (adaptive selection, active
fault plans, fused chunks, planner probe rounds). Also covers the
transport half: once-per-round broadcast encoding and the quantized int8
downlink (CommConfig.downlink_compression)."""

import dataclasses

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.algorithms.scaffold import ScaffoldAPI
from fedml_tpu.config import (
    CommConfig,
    DataConfig,
    FedConfig,
    RunConfig,
    TrainConfig,
)
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression

NUM_CLIENTS = 10
NUM_CLASSES = 4
FEAT = (6,)


def _data(ragged=False, total=NUM_CLIENTS):
    return synthetic_classification(
        num_clients=total,
        num_classes=NUM_CLASSES,
        feat_shape=FEAT,
        samples_per_client=24,
        partition_method="hetero",
        ragged=ragged,
        seed=11,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=NUM_CLASSES),
        input_shape=FEAT,
        num_classes=NUM_CLASSES,
        name="lr",
    )


def _cfg(pipeline="auto", comm_round=8, **fed_kw):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=NUM_CLIENTS,
            client_num_per_round=4,
            comm_round=comm_round,
            epochs=2,
            frequency_of_the_test=3,
            pipeline=pipeline,
            **fed_kw,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1, momentum=0.9),
        seed=3,
    )


def _tree_equal(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# byte parity: pipelined == serial
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ragged", [False, True])
def test_pipelined_matches_serial(ragged):
    data, model = _data(ragged), _model()
    serial = FedAvgAPI(_cfg("off"), data, model)
    serial.train()
    piped = FedAvgAPI(_cfg("auto"), data, model)
    piped.train()
    assert serial.pipeline_rounds == 0
    assert piped.pipeline_rounds > 0
    _tree_equal(serial.global_vars, piped.global_vars)
    for rs, rp in zip(serial.history, piped.history):
        assert rs["round"] == rp["round"]
        assert rs["Train/Loss"] == rp["Train/Loss"]
        if "Test/Acc" in rs:
            assert rs["Test/Acc"] == rp["Test/Acc"]
    # every prepared stash was consumed — nothing leaked
    assert not piped._warm_placed
    assert not piped._pipeline_overlap


def test_scaffold_pipelined_sharded_state_parity(tmp_path):
    """SCAFFOLD with the sharded on-disk state tier: the prepared batch
    rides the stash while per-client control rows keep their own
    prefetch choreography — pipelined == serial exactly, state included."""

    def mk(pipeline):
        cfg = _cfg(
            pipeline,
            comm_round=4,
            state_store="sharded",
            state_dir=str(tmp_path / pipeline),
        )
        cfg = dataclasses.replace(
            cfg, train=TrainConfig(client_optimizer="sgd", lr=0.1)
        )
        return ScaffoldAPI(cfg, _data(), _model())

    serial, piped = mk("off"), mk("auto")
    serial.train()
    piped.train()
    assert piped.pipeline_rounds > 0
    _tree_equal(serial.global_vars, piped.global_vars)
    _tree_equal(serial.c_server, piped.c_server)
    sampled = sorted(
        {int(i) for r in range(4) for i in serial._round_plan(r)[0]}
    )
    _tree_equal(
        serial._c_store.gather(sampled), piped._c_store.gather(sampled)
    )


# ---------------------------------------------------------------------------
# automatic serial degradation
# ---------------------------------------------------------------------------


def test_fault_plan_forces_serial():
    """A plan with participation faults can shrink round r+1's cohort
    based on draws the scheduler has not made yet — the pipeline must
    stand down, and numerics must match the explicit serial run."""
    plan = '{"seed": 1, "clients": {"2": {"dropout_p": 1.0}}}'
    data, model = _data(), _model()
    piped = FedAvgAPI(_cfg("auto", fault_plan=plan), data, model)
    piped.train()
    assert piped.pipeline_rounds == 0
    serial = FedAvgAPI(_cfg("off", fault_plan=plan), data, model)
    serial.train()
    _tree_equal(serial.global_vars, piped.global_vars)


def test_adaptive_selection_forces_serial():
    """power_of_choice selects round r+1 from losses reported in round r
    — preparing ahead would sample from stale signals."""
    data, model = _data(), _model()
    api = FedAvgAPI(_cfg("auto", selection="power_of_choice"), data, model)
    api.train()
    assert api.pipeline_rounds == 0


def test_fused_chunks_pipeline_only_the_eager_gaps():
    """Fused multi-round chunks place their whole chunk at dispatch — the
    pipeline must never prepare a round that a chunk will consume (the
    stash would leak), but the single eager rounds BETWEEN chunks (cut by
    eval boundaries) are fair game. Byte parity either way."""
    data, model = _data(), _model()
    piped = FedAvgAPI(_cfg("auto", fused_rounds=4), data, model)
    if piped._store is None:
        pytest.skip("device store required for fusion")
    piped.train()
    serial = FedAvgAPI(_cfg("off", fused_rounds=4), data, model)
    serial.train()
    _tree_equal(serial.global_vars, piped.global_vars)
    for rs, rp in zip(serial.history, piped.history):
        assert rs["Train/Loss"] == rp["Train/Loss"]
    assert not piped._warm_placed  # nothing prepared into a fused chunk


def test_unsupported_subclasses_stay_serial():
    from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvgAPI
    from fedml_tpu.parallel.hierarchical_sharded import HierarchicalShardedAPI
    from fedml_tpu.robustness.backdoor import BackdoorFedAvgAPI

    for cls in (HierarchicalFedAvgAPI, HierarchicalShardedAPI, BackdoorFedAvgAPI):
        assert cls._supports_pipeline is False
    assert FedAvgAPI._supports_pipeline is True


def test_pipeline_knob_validated():
    with pytest.raises(ValueError, match="pipeline"):
        FedAvgAPI(_cfg("sometimes"), _data(), _model())


# ---------------------------------------------------------------------------
# flight-recorder honesty + recompile budget
# ---------------------------------------------------------------------------


def test_flight_folds_overlap_additively():
    """Pipelined rounds fold `overlap_s`/`pipeline_depth` onto their
    records and the summary row reports totals; t_s semantics (the SLO
    watchdog's input) are untouched."""
    from fedml_tpu.telemetry import get_tracer
    from fedml_tpu.telemetry.flight import FlightRecorder

    rec = FlightRecorder(max_rounds=16)
    rec.attach(get_tracer())
    try:
        api = FedAvgAPI(_cfg("auto"), _data(), _model())
        api.train()
    finally:
        rec.detach()
    tail = rec.tail()
    overlapped = [r for r in tail if "overlap_s" in r]
    assert len(overlapped) == api.pipeline_rounds > 0
    for r in overlapped:
        assert r["overlap_s"] >= 0.0
        assert r["pipeline_depth"] == 1
        assert r["t_s"] >= 0.0
    row = rec.summary_row()
    assert row["flight/pipelined_rounds"] == api.pipeline_rounds
    assert row["flight/overlap_s"] >= 0.0
    # round 0 has no previous round to hide behind — never pipelined
    assert "overlap_s" not in tail[0]


@pytest.fixture
def warmed_pipelined_api():
    """Warmup runs BEFORE the sentinel starts, so the budget window is
    exactly the post-warmup pipelined train loop."""
    data, model = _data(), _model()
    cold = FedAvgAPI(_cfg("off"), data, model)
    cold.train()
    warm = FedAvgAPI(_cfg("auto"), data, model)
    warm.warmup(log_fn=lambda r: None)
    return cold, warm


@pytest.mark.recompile_budget(0)
def test_pipelined_run_post_warmup_compiles_nothing(
    warmed_pipelined_api, recompile_sentinel
):
    """Preparing round r+1 ahead reuses the exact placement/gather
    programs warmup enumerated — zero lazy compiles, byte parity."""
    cold, warm = warmed_pipelined_api
    warm.train()
    assert warm.pipeline_rounds > 0
    _tree_equal(cold.global_vars, warm.global_vars)


# ---------------------------------------------------------------------------
# transport: once-per-round broadcast + quantized downlink
# ---------------------------------------------------------------------------


def _transport_cfg(dl="none", uplink="none", workers=6):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=workers,
            client_num_per_round=workers,
            comm_round=4,
            epochs=1,
            frequency_of_the_test=1,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        comm=CommConfig(downlink_compression=dl, compression=uplink),
        seed=3,
    )


def test_broadcast_shares_one_encoded_payload():
    """Every worker's sync message must reference the SAME host buffers —
    one model copy per round, not one per worker."""
    from fedml_tpu.algorithms.fedavg_transport import FedAvgServerManager
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
    from fedml_tpu.core.message import MessageType as MT

    cfg = _transport_cfg()
    srv = FedAvgServerManager(
        cfg, LoopbackCommManager(LoopbackHub(), 0), _model(),
        data=_data(total=6), worker_num=6,
    )
    sent = []
    srv._broadcast = lambda msg: (sent.append(msg), True)[1]
    srv._broadcast_round(MT.S2C_SYNC_MODEL, 0, list(range(6)))
    assert len(sent) == 6
    ref_leaves = jax.tree_util.tree_leaves(sent[0].get(MT.ARG_MODEL_PARAMS))
    for msg in sent[1:]:
        for a, b in zip(
            ref_leaves, jax.tree_util.tree_leaves(msg.get(MT.ARG_MODEL_PARAMS))
        ):
            assert a is b  # identity: shared buffers, no per-worker copy
    # the round's reference model IS the shipped tree
    for a, b in zip(
        ref_leaves, jax.tree_util.tree_leaves(srv.global_vars)
    ):
        assert a is b


def test_downlink_int8_loopback_cuts_bytes_at_close_loss():
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation
    from fedml_tpu.telemetry import get_comm_meter

    data, model = _data(total=6), _model()
    base_snap = get_comm_meter().snapshot()
    srv_fp32 = run_loopback_federation(_transport_cfg("none"), data, model)
    mid_snap = get_comm_meter().snapshot()
    srv_int8 = run_loopback_federation(_transport_cfg("int8"), data, model)
    end_snap = get_comm_meter().snapshot()

    def d(a, b, k):
        return b.get(k, 0) - a.get(k, 0)

    # fp32 arm: payload == raw (exact downlink)
    assert d(base_snap, mid_snap, "downlink_payload_bytes") == d(
        base_snap, mid_snap, "downlink_raw_bytes"
    ) > 0
    # int8 arm: >= 2x cut (4x on the q arrays; scales dilute small models)
    pay = d(mid_snap, end_snap, "downlink_payload_bytes")
    raw = d(mid_snap, end_snap, "downlink_raw_bytes")
    assert raw / pay >= 2.0, (raw, pay)
    assert d(mid_snap, end_snap, "downlink_updates") == 4 * 6
    # matched reach: final eval loss within tolerance of the exact arm
    assert abs(
        srv_fp32.history[-1]["Test/Loss"] - srv_int8.history[-1]["Test/Loss"]
    ) < 0.05


def test_downlink_int8_composes_with_uplink_compression():
    """Uplink deltas encode against the dequantized broadcast tree and the
    server decodes against the SAME tree — the round must close with sane
    numerics, proving the two references never diverged."""
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation

    data, model = _data(total=6), _model()
    exact = run_loopback_federation(_transport_cfg(), data, model)
    both = run_loopback_federation(
        _transport_cfg("int8", uplink="int8"), data, model
    )
    assert abs(
        exact.history[-1]["Test/Loss"] - both.history[-1]["Test/Loss"]
    ) < 0.05


def test_secure_agg_rejects_downlink_compression():
    from fedml_tpu.algorithms.fedavg_transport import FedAvgServerManager
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub

    cfg = _transport_cfg("int8")
    cfg = dataclasses.replace(
        cfg, comm=dataclasses.replace(cfg.comm, secure_agg=True)
    )
    with pytest.raises(ValueError, match="mutually exclusive"):
        FedAvgServerManager(
            cfg, LoopbackCommManager(LoopbackHub(), 0), _model(), worker_num=6
        )
