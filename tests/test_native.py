"""Native fastpack library: builds with g++, matches the numpy fallback
bit-for-bit, and the integrated paths (stack_clients, Message.to_bytes)
produce identical results with and without it."""

import numpy as np
import pytest

from fedml_tpu import native


def test_native_builds():
    # the image bakes g++, so the native path must actually build here
    assert native.available()


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.normal(size=(100, 7, 3)).astype(np.float32)
    order = rng.permutation(100)[:60]
    out_native = np.zeros((60, 7, 3), np.float32)
    native.gather_rows(src, order, out_native)
    np.testing.assert_array_equal(out_native, src[order])
    # int labels too
    srci = rng.integers(0, 50, size=(33,)).astype(np.int32)
    outi = np.zeros((10,), np.int32)
    native.gather_rows(srci, np.arange(10), outi)
    np.testing.assert_array_equal(outi, srci[:10])


def test_gather_rows_noncontiguous_fallback():
    rng = np.random.default_rng(1)
    src = rng.normal(size=(20, 4)).astype(np.float32)
    out = np.zeros((40, 4), np.float32)[::2]  # non-contiguous destination
    native.gather_rows(src, np.arange(20), out)
    np.testing.assert_array_equal(out, src)


def test_concat_buffers():
    bufs = [bytes([i]) * (i * 100 + 1) for i in range(10)]
    assert native.concat_buffers(bufs, header=b"HDR") == b"HDR" + b"".join(bufs)
    assert native.concat_buffers([], header=b"X") == b"X"


def test_message_roundtrip_uses_native(monkeypatch):
    from fedml_tpu.core.message import Message

    m = Message("t", 0, 1)
    tree = {"w": np.arange(1000, dtype=np.float32)}
    m.add_params("params", tree)
    wire_native = m.to_bytes()
    # force fallback and compare byte-for-byte
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_failed", True)
    wire_fallback = m.to_bytes()
    assert wire_native == wire_fallback
    out = Message.from_bytes(wire_native)
    np.testing.assert_array_equal(out.get("params")["w"], tree["w"])
