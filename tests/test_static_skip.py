"""Static cond-skip selection (resolve_skip_empty_steps).

The per-step lax.cond that skips all-padding local steps costs real time
even when every step has data (measured +50% per step on the cross-silo
ResNet-56 round), so whether to emit it is decided per cohort from
host-side sample counts. These tests pin:
- the host-side predicate (_cohort_may_pad) against the bucket contract;
- that the dispatcher compiles the cond-less variant for pad-free
  cohorts and the cond variant for padded ones;
- that both variants produce identical round math on the SAME padded
  batch (the where-gated no-skip path and the cond-skip path must agree
  bitwise-closely, or the variant choice would change results).
"""

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI, client_sampling
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model


def _api(samples_per_client, partition="homo", batch_size=4, momentum=0.9):
    num_clients = 4
    data = synthetic_classification(
        num_clients=num_clients,
        num_classes=3,
        feat_shape=(6,),
        samples_per_client=samples_per_client,
        partition_method=partition,
        ragged=(partition != "homo"),
        seed=0,
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=batch_size, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=num_clients,
            client_num_per_round=num_clients,
            comm_round=2,
            epochs=2,
            client_parallelism="scan",
            frequency_of_the_test=10_000,
        ),
        # momentum makes a skipped-vs-computed padding step observable if
        # the gating were wrong (momentum state must not move on padding)
        train=TrainConfig(client_optimizer="sgd", lr=0.1, momentum=momentum),
        model="lr",
    )
    model = create_model("lr", "synthetic", (6,), 3)
    return FedAvgAPI(cfg, data, model)


def test_cohort_may_pad_predicate():
    api = _api(samples_per_client=8, batch_size=4)  # 8 = 2 full steps, pow2
    sampled = client_sampling(0, 4, 4)
    assert api._cohort_may_pad(sampled) is False
    # force_steps above the real step count introduces all-padding steps
    assert api._cohort_may_pad(sampled, force_steps=4) is True

    ragged = _api(samples_per_client=8, partition="hetero", batch_size=4)
    sampled = client_sampling(0, 4, 4)
    counts = ragged._client_counts(sampled)
    from fedml_tpu.data.base import bucket_steps

    steps, bs, _ = bucket_steps(counts, 4, 1)
    expect = any(-(-n // bs) < steps for n in counts)
    assert ragged._cohort_may_pad(sampled) is expect


def test_dispatcher_compiles_matching_variant():
    api = _api(samples_per_client=8, batch_size=4)
    assert api.round_fn.supports_may_pad
    api.train_round(0)
    assert set(api.round_fn._variants) == {False}

    # a ragged cohort with an all-padding step picks the cond variant
    ragged = _api(samples_per_client=9, batch_size=4)  # 3 steps -> pow2 4
    sampled = client_sampling(0, 4, 4)
    assert ragged._cohort_may_pad(sampled) is True
    ragged.train_round(0)
    assert set(ragged.round_fn._variants) == {True}


def test_variants_identical_math_on_padded_batch():
    """Run the SAME padded round through both variants: cond-skip and
    where-gated must agree (incl. momentum state effects across 2 epochs)."""
    api = _api(samples_per_client=9, batch_size=4)
    sampled = client_sampling(0, 4, 4)
    batch = api._round_batch(sampled, 0)
    rng = jax.random.fold_in(api.rng, 1)
    placed = api._place_batch(batch, rng)

    gv0 = jax.tree_util.tree_map(lambda a: a.copy(), api.global_vars)
    out_skip, met_skip = api.round_fn(gv0, *placed, may_pad=True)
    gv1 = jax.tree_util.tree_map(lambda a: a.copy(), api.global_vars)
    out_gate, met_gate = api.round_fn(gv1, *placed, may_pad=False)

    assert set(api.round_fn._variants) == {True, False}
    for a, b in zip(
        jax.tree_util.tree_leaves(out_skip), jax.tree_util.tree_leaves(out_gate)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)
    for k in met_skip:
        np.testing.assert_allclose(
            float(met_skip[k]), float(met_gate[k]), rtol=1e-6
        )


def test_fused_chunk_keys_carry_may_pad():
    import dataclasses

    api = _api(samples_per_client=8, batch_size=4)
    api.config = dataclasses.replace(
        api.config,
        fed=dataclasses.replace(api.config.fed, fused_rounds=2),
    )
    if api._store is None:
        pytest.skip("device store unavailable")
    api.train_rounds_fused(0, 2)
    keys = list(api._fused_fns)
    assert keys and all(len(k) == 3 for k in keys)
    # uniform 8-sample clients at bs=4: exactly 2 steps, no padding
    assert keys[0][2] is False
