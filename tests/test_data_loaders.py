"""Data-layer tests: each loader is fed a tiny fixture written in the real
on-disk format (leaf JSON, TFF h5, CIFAR pickle, stackoverflow h5+sidecars) —
the reference has no loader tests at all (SURVEY §4); its CI downloads real
datasets, which a zero-egress environment cannot."""

import json
import os
import pickle

import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig
from fedml_tpu.data import registry
from fedml_tpu.data.text import PAD_ID, VOCAB_SIZE, preprocess_snippets, split_xy


def test_text_preprocess_roundtrip():
    seqs = preprocess_snippets(["hello world"], max_seq_len=8)
    assert seqs.shape[1] == 9
    x, y = split_xy(seqs)
    assert x.shape == y.shape
    # y is x shifted by one position
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
    assert seqs.max() < VOCAB_SIZE


def _write_leaf(tmpdir, num_clients=3, dim=784):
    rng = np.random.default_rng(0)
    for split, n in (("train", 10), ("test", 4)):
        d = os.path.join(tmpdir, split)
        os.makedirs(d, exist_ok=True)
        users = [f"u{i}" for i in range(num_clients)]
        user_data = {
            u: {
                "x": rng.normal(size=(n, dim)).tolist(),
                "y": rng.integers(0, 10, size=n).tolist(),
            }
            for u in users
        }
        with open(os.path.join(d, "all_data.json"), "w") as f:
            json.dump(
                {"users": users, "user_data": user_data, "num_samples": [n] * num_clients},
                f,
            )


def test_leaf_mnist_loader(tmp_path):
    _write_leaf(str(tmp_path))
    from fedml_tpu.data.leaf import load_mnist

    ds = load_mnist(str(tmp_path))
    assert ds.num_clients == 3
    assert ds.client_x[0].shape == (10, 28, 28, 1)
    assert ds.test_x.shape == (12, 28, 28, 1)
    assert ds.num_classes == 10


def test_leaf_shakespeare_loader(tmp_path):
    for split, n in (("train", 6), ("test", 2)):
        d = tmp_path / split
        d.mkdir()
        users = ["a", "b"]
        user_data = {
            u: {"x": ["the quick brown fox jumps over!" * 3][:1] * n, "y": ["t"] * n}
            for u in users
        }
        (d / "data.json").write_text(
            json.dumps({"users": users, "user_data": user_data})
        )
    from fedml_tpu.data.leaf import load_shakespeare

    ds = load_shakespeare(str(tmp_path))
    assert ds.num_clients == 2
    assert ds.client_x[0].dtype == np.int32
    assert ds.client_y[0].shape == (6,)


def _write_tff_femnist(tmp_path):
    import h5py

    rng = np.random.default_rng(1)
    for fname, n in (("fed_emnist_train.h5", 8), ("fed_emnist_test.h5", 3)):
        with h5py.File(tmp_path / fname, "w") as f:
            for cid in ("c0", "c1"):
                g = f.create_group(f"examples/{cid}")
                g.create_dataset("pixels", data=rng.random((n, 28, 28)), dtype="f4")
                g.create_dataset(
                    "label", data=rng.integers(0, 62, n), dtype="i8"
                )


def test_tff_femnist_loader(tmp_path):
    _write_tff_femnist(tmp_path)
    from fedml_tpu.data.tff_h5 import load_femnist

    ds = load_femnist(str(tmp_path))
    assert ds.num_clients == 2
    assert ds.client_x[0].shape == (8, 28, 28, 1)
    assert ds.test_y.shape == (6,)
    assert ds.num_classes == 62


def test_tff_fed_shakespeare_loader(tmp_path):
    import h5py

    for fname in ("shakespeare_train.h5", "shakespeare_test.h5"):
        with h5py.File(tmp_path / fname, "w") as f:
            for cid in ("p0", "p1"):
                g = f.create_group(f"examples/{cid}")
                g.create_dataset(
                    "snippets",
                    data=[b"to be or not to be that is the question" * 4],
                )
    from fedml_tpu.data.tff_h5 import load_fed_shakespeare

    ds = load_fed_shakespeare(str(tmp_path))
    assert ds.num_clients == 2
    assert ds.client_x[0].shape[1] == 80
    assert (ds.client_x[0][:, 1:] == ds.client_y[0][:, :-1]).all()


def test_tff_fed_cifar100_loader(tmp_path):
    import h5py

    rng = np.random.default_rng(2)
    for fname, n in (("fed_cifar100_train.h5", 6), ("fed_cifar100_test.h5", 4)):
        with h5py.File(tmp_path / fname, "w") as f:
            for cid in ("c0", "c1", "c2"):
                g = f.create_group(f"examples/{cid}")
                g.create_dataset(
                    "image", data=rng.integers(0, 255, (n, 32, 32, 3)), dtype="u1"
                )
                g.create_dataset("label", data=rng.integers(0, 100, n), dtype="i8")
    from fedml_tpu.data.tff_h5 import load_fed_cifar100

    ds = load_fed_cifar100(str(tmp_path))
    assert ds.num_clients == 3
    assert ds.client_x[0].shape == (6, 24, 24, 3)
    assert ds.num_classes == 100


def _write_cifar10(tmp_path):
    rng = np.random.default_rng(3)
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    for i in range(1, 6):
        batch = {
            b"data": rng.integers(0, 255, (20, 3072), dtype=np.uint8).astype(np.uint8),
            b"labels": rng.integers(0, 10, 20).tolist(),
        }
        with open(d / f"data_batch_{i}", "wb") as f:
            pickle.dump(batch, f)
    with open(d / "test_batch", "wb") as f:
        pickle.dump(
            {
                b"data": rng.integers(0, 255, (10, 3072), dtype=np.uint8),
                b"labels": rng.integers(0, 10, 10).tolist(),
            },
            f,
        )


def test_cifar10_lda_loader(tmp_path):
    _write_cifar10(tmp_path)
    from fedml_tpu.data.cifar import load_cifar_family

    ds = load_cifar_family("cifar10", str(tmp_path), num_clients=5, partition_alpha=0.5)
    assert ds.num_clients == 5
    assert sum(len(y) for y in ds.client_y) == 100
    assert ds.client_x[0].shape[1:] == (32, 32, 3)
    assert ds.test_x.shape == (10, 32, 32, 3)
    # normalized, not raw uint8
    assert ds.client_x[0].dtype == np.float32 and abs(ds.client_x[0]).max() < 10


def _write_stackoverflow(tmp_path):
    import h5py

    words = [f"w{i}" for i in range(50)]
    (tmp_path / "stackoverflow.word_count").write_text(
        "".join(f"{w} {100 - i}\n" for i, w in enumerate(words))
    )
    (tmp_path / "stackoverflow.tag_count").write_text(
        json.dumps({f"t{i}": 10 - i for i in range(10)})
    )
    for fname in ("stackoverflow_train.h5", "stackoverflow_test.h5"):
        with h5py.File(tmp_path / fname, "w") as f:
            for cid in ("u0", "u1"):
                g = f.create_group(f"examples/{cid}")
                g.create_dataset("tokens", data=[b"w1 w2 w3", b"w4 w5 unknown"])
                g.create_dataset("title", data=[b"w1", b"w9"])
                g.create_dataset("tags", data=[b"t1|t2", b"t3"])


def test_stackoverflow_lr_loader(tmp_path):
    _write_stackoverflow(tmp_path)
    from fedml_tpu.data.stackoverflow import load_stackoverflow_lr

    ds = load_stackoverflow_lr(str(tmp_path), vocab_size=50, tag_size=10)
    assert ds.num_clients == 2
    assert ds.client_x[0].shape == (2, 50)
    assert ds.client_y[0].shape == (2, 10)
    assert ds.client_y[0][0, 1] == 1.0 and ds.client_y[0][0, 2] == 1.0


def test_stackoverflow_nwp_loader(tmp_path):
    _write_stackoverflow(tmp_path)
    from fedml_tpu.data.stackoverflow import load_stackoverflow_nwp

    ds = load_stackoverflow_nwp(str(tmp_path), vocab_size=50, max_seq_len=6)
    assert ds.num_clients == 2
    assert ds.client_x[0].shape == (2, 6)
    # bos at position 0
    assert (ds.client_x[0][:, 0] == 51).all()


def test_registry_dispatch_synthetic():
    cfg = RunConfig(
        data=DataConfig(dataset="synthetic_0.5_0.5"),
        fed=FedConfig(client_num_in_total=6),
    )
    ds = registry.load(cfg)
    assert ds.num_clients == 6
    assert registry.task_for_dataset("stackoverflow_nwp") == "nwp"
    assert registry.task_for_dataset("stackoverflow_lr") == "tag"
    assert registry.task_for_dataset("cifar10") == "classification"


def test_registry_missing_data_raises(tmp_path):
    cfg = RunConfig(
        data=DataConfig(dataset="mnist", data_dir=str(tmp_path / "nope")),
        fed=FedConfig(client_num_in_total=3),
    )
    with pytest.raises(FileNotFoundError):
        registry.load(cfg)
