"""Measured fused-vs-eager planner (algorithms/round_planner.py, ISSUE 14):
probe both schedules off flight-recorder folds, commit the measured winner
per (algorithm, shape-class, cohort). Contracts pinned here: decisions are
a DETERMINISTIC function of the observed record stream (same flight
history ⇒ same choice), schedule choice never touches numerics (measured
run == static run bit-for-bit at matching seeds), and the planner detaches
from the span stream once every key has committed."""

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.algorithms.round_planner import (
    PROBE_SAMPLES,
    PlanKey,
    SchedulePlanner,
)
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression

KEY = PlanKey(algo="FedAvgAPI", steps=3, bs=8, cohort=4)


def _drive(history):
    """Replay a (round -> per-round cost) probe history into a fresh
    planner exactly the way a run would: plan, then fold. Returns the
    planner. ``history`` rows: (round_idx, fusible_len, t_s, fused_rounds
    or None)."""
    p = SchedulePlanner()
    for r, fusible, t_s, fused in history:
        p.plan(KEY, r, fusible)
        rec = {"round": r, "t_s": t_s}
        if fused:
            rec["fused_rounds"] = fused
        p.observe(rec)
    return p


def _history(fused_chunk_s, eager_round_s, L=4):
    """The canonical probe transcript: PROBE_SAMPLES fused chunks then
    PROBE_SAMPLES eager rounds."""
    rows = []
    r = 0
    for c in fused_chunk_s[:PROBE_SAMPLES]:
        rows.append((r, L, c, L))
        r += L
    for e in eager_round_s[:PROBE_SAMPLES]:
        rows.append((r, L, e, None))
        r += 1
    return rows


def test_same_history_same_choice():
    """Determinism: the decision is a pure function of the record
    stream — replaying identical histories always commits identically."""
    hist = _history([4.0, 3.6], [1.5, 1.2])  # fused 0.9/round vs eager 1.2
    decisions = {_drive(hist).decision(KEY) for _ in range(5)}
    assert decisions == {"fused"}
    # reversed costs flip the decision, deterministically too
    hist2 = _history([8.0, 7.2], [1.5, 1.2])  # fused 1.8/round vs eager 1.2
    assert {_drive(hist2).decision(KEY) for _ in range(5)} == {"eager"}


def test_min_statistic_ignores_compile_tainted_first_sample():
    """A slow first sample (lazy compile, cold cache) must not decide:
    min-of-K keeps the clean sample."""
    hist = _history([40.0, 3.6], [1.5, 1.2])  # first chunk compile-tainted
    assert _drive(hist).decision(KEY) == "fused"


def test_tie_breaks_toward_fused():
    hist = _history([4.8, 4.8], [1.2, 1.2])  # both 1.2 s/round exactly
    assert _drive(hist).decision(KEY) == "fused"


def test_probe_schedule_and_idempotence():
    p = SchedulePlanner()
    # fused arm fills first (PROBE_SAMPLES chunks), then eager
    assert p.plan(KEY, 0, 4) == 4
    assert p.plan(KEY, 0, 4) == 4  # idempotent per round (warmup re-asks)
    assert p.wants_sync(0)
    p.observe({"round": 0, "t_s": 4.0, "fused_rounds": 4})
    assert not p.wants_sync(0)
    assert p.plan(KEY, 4, 4) == 4
    p.observe({"round": 4, "t_s": 4.0, "fused_rounds": 4})
    assert p.plan(KEY, 8, 4) == 1  # eager arm
    p.observe({"round": 8, "t_s": 0.9})
    assert p.plan(KEY, 9, 4) == 1
    p.observe({"round": 9, "t_s": 0.9})
    # committed: eager (0.9 < 1.0) — and no more probe syncs anywhere
    assert p.decision(KEY) == "eager"
    assert p.plan(KEY, 10, 4) == 1
    assert not p.wants_sync(10)
    row = p.summary_row()
    assert row["flight/planner_schedule"] == "eager"
    assert row["flight/probe_fused_per_round_s"] == 1.0
    assert row["flight/probe_eager_per_round_s"] == 0.9


def test_walk_ahead_defaults_fused_without_probing():
    """A caller planning ahead of execution (the warmup chunk walk asks
    about many future rounds before any fold lands) gets the amortizing
    default for rounds beyond the probe window — NOT extra probe
    segments that would never fold."""
    p = SchedulePlanner()
    for r in (0, 4):
        assert p.plan(KEY, r, 4) == 4  # fused probe arm
    for r in (8, 9):
        assert p.plan(KEY, r, 4) == 1  # eager probe arm
    # beyond the window, undecided: fused default, no pending registered
    assert p.plan(KEY, 10, 4) == 4
    assert not p.wants_sync(10)


def test_unrelated_records_ignored():
    p = SchedulePlanner()
    p.plan(KEY, 0, 4)
    p.observe({"round": 99, "t_s": 123.0})  # not a probe segment
    assert p.decision(KEY) is None
    assert p.wants_sync(0)


def _lr_setup(plan, fused_rounds=4, comm_round=16, seed=3):
    data = synthetic_classification(
        num_clients=16, num_classes=4, feat_shape=(6,),
        samples_per_client=24, partition_method="homo", seed=11,
    )
    model = ModelDef(
        module=LogisticRegression(num_classes=4), input_shape=(6,),
        num_classes=4, name="lr",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=16, client_num_per_round=4,
            comm_round=comm_round, epochs=1, frequency_of_the_test=10_000,
            fused_rounds=fused_rounds, fused_plan=plan,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=seed,
    )
    return cfg, data, model


@pytest.mark.recompile_budget(60)
def test_measured_plan_numerics_match_static(recompile_sentinel):
    """The schedule decision can change WALL time only: a measured-plan
    run's history and final model are bit-identical to the static plan's
    (fused == eager is already a test contract; the planner only picks
    between them)."""
    cfg_m, data, model = _lr_setup("measured")
    api_m = FedAvgAPI(cfg_m, data, model)
    assert api_m._store is not None, "device store required for this test"
    api_m.train()
    assert api_m.planner is not None
    row = api_m.planner.summary_row()
    assert row.get("flight/planner_schedule") in ("fused", "eager")
    assert row.get("flight/probe_fused_per_round_s") is not None
    assert row.get("flight/probe_eager_per_round_s") is not None

    cfg_s, _, _ = _lr_setup("static")
    api_s = FedAvgAPI(cfg_s, data, model)
    api_s.train()
    for a, b in zip(
        jax.tree_util.tree_leaves(api_m.global_vars),
        jax.tree_util.tree_leaves(api_s.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for rm, rs in zip(api_m.history, api_s.history):
        assert rm["round"] == rs["round"]
        assert rm["Train/Loss"] == rs["Train/Loss"]


def test_planner_detaches_after_commit():
    """Once every key committed, the planner stops listening (and a
    privately-attached recorder leaves the tracer) — steady-state rounds
    carry zero probe overhead and no listener leak across runs."""
    from fedml_tpu.telemetry import get_tracer

    baseline = len(get_tracer().listeners())
    cfg, data, model = _lr_setup("measured", comm_round=24)
    api = FedAvgAPI(cfg, data, model)
    assert len(get_tracer().listeners()) > baseline  # probe listening
    api.train()
    assert api.planner.summary_row().get("flight/planner_schedule")
    assert len(get_tracer().listeners()) == baseline


def test_new_key_after_commit_reattaches_and_commits():
    """A PlanKey first seen AFTER the probe closed (mid-run cohort or
    steps-class change) re-subscribes the planner to the fold stream —
    its probes are observed, it commits on its own measurements, and the
    planner detaches again, with zero probe bookkeeping left behind."""
    from fedml_tpu.telemetry import get_tracer

    baseline = len(get_tracer().listeners())
    p = SchedulePlanner().attach(get_tracer())
    for r, fusible, t_s, fused in _history([4.0, 3.6], [1.5, 1.2]):
        p.plan(KEY, r, fusible)
        rec = {"round": r, "t_s": t_s}
        if fused:
            rec["fused_rounds"] = fused
        p.observe(rec)
    assert p.decision(KEY) == "fused"
    assert len(get_tracer().listeners()) == baseline  # detached
    # a NEW key appears: the planner must re-attach and probe it
    key2 = PlanKey(algo="FedAvgAPI", steps=3, bs=8, cohort=2)
    assert p.plan(key2, 100, 4) == 4
    assert len(get_tracer().listeners()) > baseline  # listening again
    hist2 = [(100, 4, 8.0, 4), (104, 4, 7.2, 4), (108, 4, 1.2, None),
             (109, 4, 1.1, None)]
    for r, fusible, t_s, fused in hist2:
        p.plan(key2, r, fusible)
        rec = {"round": r, "t_s": t_s}
        if fused:
            rec["fused_rounds"] = fused
        p.observe(rec)
    assert p.decision(key2) == "eager"  # measured on ITS OWN probes
    assert p.decision(KEY) == "fused"  # first key untouched
    assert len(get_tracer().listeners()) == baseline  # detached again
    assert not p._planned and not p._pending  # steady state holds nothing


def test_committed_plan_holds_no_per_round_state():
    """Post-commit plan() answers are pure functions of the decision —
    a 100k-round run must not grow one cache entry per round."""
    hist = _history([4.0, 3.6], [1.5, 1.2])
    p = _drive(hist)
    assert p.decision(KEY) == "fused"
    for r in range(200, 1200):
        assert p.plan(KEY, r, 4) == 4
    assert not p._planned


def test_static_plan_has_no_planner():
    cfg, data, model = _lr_setup("static")
    assert FedAvgAPI(cfg, data, model).planner is None


def test_invalid_plan_rejected():
    cfg, data, model = _lr_setup("static")
    import dataclasses

    bad = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, fused_plan="vibes")
    )
    with pytest.raises(ValueError, match="fused_plan"):
        FedAvgAPI(bad, data, model)
