"""FedNova on the mesh runtime == the vmap runtime (normalized averaging
with ragged per-client step counts; the reference's fednova is
standalone-only)."""

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fednova import FedNovaAPI
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.parallel import DistributedFedNovaAPI


@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_mesh_fednova_matches_vmap(momentum):
    # ragged shards => heterogeneous tau_i, the case FedNova exists for
    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(5,), samples_per_client=24,
        partition_method="homo", ragged=True, seed=6,
    )
    model = ModelDef(
        LogisticRegression(num_classes=3), input_shape=(5,), num_classes=3,
        name="lr",
    )
    cfg = RunConfig(
        data=DataConfig(batch_size=4, pad_bucket=1),
        fed=FedConfig(
            client_num_in_total=8, client_num_per_round=8, comm_round=2,
            epochs=1, frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1, momentum=momentum),
        seed=0,
    )
    sim = FedNovaAPI(cfg, data, model)
    mesh_api = DistributedFedNovaAPI(cfg, data, model)
    assert {len(data.client_y[i]) for i in range(8)} != {24}  # truly ragged
    for r in range(cfg.fed.comm_round):
        _, m_sim = sim.train_round(r)
        _, m_mesh = mesh_api.train_round(r)
        np.testing.assert_allclose(
            float(m_sim["steps"]), float(m_mesh["steps"])
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(mesh_api.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )
