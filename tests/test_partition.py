import numpy as np

from fedml_tpu.partition import homo_partition, lda_partition, record_data_stats


def test_homo_partition_covers_all():
    rng = np.random.default_rng(0)
    parts = homo_partition(103, 7, rng)
    all_idx = np.sort(np.concatenate(list(parts.values())))
    assert np.array_equal(all_idx, np.arange(103))


def test_lda_partition_covers_all_and_min_size():
    labels = np.random.default_rng(1).integers(0, 10, size=2000)
    parts = lda_partition(labels, 20, alpha=0.5, seed=3, min_size=10)
    all_idx = np.sort(np.concatenate(list(parts.values())))
    assert np.array_equal(all_idx, np.arange(2000))
    assert min(len(v) for v in parts.values()) >= 10


def test_lda_partition_is_skewed():
    # Low alpha must produce label skew: some client has a dominant class.
    labels = np.random.default_rng(2).integers(0, 10, size=5000)
    parts = lda_partition(labels, 10, alpha=0.1, seed=0)
    stats = record_data_stats(labels, parts)
    top_fracs = []
    for hist in stats.values():
        tot = sum(hist.values())
        top_fracs.append(max(hist.values()) / tot)
    assert max(top_fracs) > 0.5


def test_lda_partition_deterministic():
    labels = np.random.default_rng(3).integers(0, 5, size=500)
    a = lda_partition(labels, 5, 0.5, seed=7)
    b = lda_partition(labels, 5, 0.5, seed=7)
    for k in a:
        assert np.array_equal(a[k], b[k])
