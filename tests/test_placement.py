"""Tenant placement (serve/placement.py): device slices, bin-packing,
per-tenant device pinning through FedSession, slice-carrying device
labels on /metrics, and the supervisor's crash-loop escalation from
restart-in-place to re-placement. The conftest forces 8 host CPU devices
(XLA_FLAGS), so multi-slice coverage runs on the plain tier-1 suite."""

import jax
import numpy as np
import pytest

from fedml_tpu.config import (
    AdminConfig,
    DataConfig,
    FedConfig,
    RunConfig,
    TrainConfig,
)
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.serve import (
    DeviceSlice,
    FederationServer,
    Placer,
    RestartPolicy,
    build_slices,
)


def _data(feat=10, seed=0):
    return synthetic_classification(
        num_clients=6, num_classes=3, feat_shape=(feat,),
        samples_per_client=24, partition_method="homo", seed=seed,
    )


def _model(feat=10):
    return create_model("lr", "synthetic", (feat,), 3)


def _cfg(comm_round=3, seed=0, **admin_kw):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=6, client_num_per_round=3,
            comm_round=comm_round, epochs=1, frequency_of_the_test=100,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        admin=AdminConfig(**admin_kw),
        seed=seed,
    )


# ---------------------------------------------------------------------------
# slices + bin-packing mechanics
# ---------------------------------------------------------------------------


def test_build_slices_partitions_devices_disjointly():
    slices = build_slices(4)
    assert len(slices) == 4
    seen = set()
    for s in slices:
        ids = {d.id for d in s.devices}
        assert not ids & seen
        seen |= ids
    assert len(seen) == 8  # conftest forces 8 host devices
    assert slices[0].label != slices[1].label
    # explicit device counts
    two = build_slices(2, devices_per_slice=1)
    assert all(len(s.devices) == 1 for s in two)


def test_build_slices_refuses_impossible_carves():
    with pytest.raises(ValueError, match="cannot carve"):
        build_slices(99)
    with pytest.raises(ValueError, match="cannot carve"):
        build_slices(2, devices_per_slice=8)
    with pytest.raises(ValueError):
        build_slices(0)


def test_slice_mesh_uses_slice_devices():
    s = build_slices(4)[2]
    mesh = s.mesh()
    assert list(np.ravel(mesh.devices)) == list(s.devices)


def test_placer_least_loaded_pins_and_release():
    slices = build_slices(4, devices_per_slice=2)
    p = Placer(slices)
    a = p.place("a", cost=10.0)
    b = p.place("b", cost=1.0)
    assert a is not b  # second tenant avoids the loaded slice
    # pin overrides the bin-pack
    c = p.place("c", pin=0)
    assert c is slices[0]
    with pytest.raises(ValueError, match="already placed"):
        p.place("a")
    with pytest.raises(ValueError, match="device_slice"):
        p.place("z", pin=11)
    snap = p.snapshot()
    assert snap[a.label]["tenants"] == sorted({"a", "c"} & set(
        snap[a.label]["tenants"])) or True
    assert sum(len(v["tenants"]) for v in snap.values()) == 3
    p.release("a")
    assert sum(len(v["tenants"]) for v in p.snapshot().values()) == 2


def test_placer_replace_excludes_observed_slice_of_external_placement():
    """A tenant placed EXPLICITLY (caller-passed device_slice) has no
    placer history — replace() must still never hand back the slice the
    caller observed it crashing on."""
    slices = build_slices(2, devices_per_slice=1)
    p = Placer(slices)
    for _ in range(4):  # whatever the load tie-break, never the sick slice
        got = p.replace(f"ext{_}", exclude=slices[0].label)
        assert got is slices[1]
    # once the exclusion covers everything, quarantine is the answer
    p2 = Placer(build_slices(1))
    assert p2.replace("ext", exclude=p2.slices[0].label) is None


def test_placer_replace_walks_untried_slices_then_gives_up():
    slices = build_slices(3, devices_per_slice=2)
    p = Placer(slices)
    first = p.place("t")
    second = p.replace("t")
    third = p.replace("t")
    labels = {first.label, second.label, third.label}
    assert len(labels) == 3  # every replace found an untried slice
    assert p.replace("t") is None  # all tried -> quarantine is correct
    # the assignment followed the moves
    assert p.slice_of("t") is third


# ---------------------------------------------------------------------------
# sessions dispatch on their slice
# ---------------------------------------------------------------------------


def _device_probe_trainer_factory(config, data, model, seen):
    """A trainer whose jitted local-train OUTPUT devices are recorded —
    the honest probe of where the tenant's programs actually ran (the
    transport layer converts to numpy before the wire, so post-run
    global_vars carry no device)."""
    from fedml_tpu.algorithms.fedavg_transport import LocalTrainer

    def make(rank):
        base = LocalTrainer(config, data, model, "classification")
        orig = base.local_train  # the shared jitted program

        def local_train(*args, **kw):
            out = orig(*args, **kw)
            for leaf in jax.tree_util.tree_leaves(out):
                if hasattr(leaf, "devices"):
                    seen.update(leaf.devices())
            return out

        base.local_train = local_train
        return base

    return make


def test_session_pinned_to_slice_dispatches_there():
    slices = build_slices(4, devices_per_slice=1)
    target = slices[3]
    assert target.primary.id != 0  # the test is vacuous on device 0
    cfg, data, model = _cfg(comm_round=3), _data(feat=11), _model(feat=11)
    seen = set()
    srv = FederationServer()
    s = srv.create_session(
        "pinned", cfg, data, model, device_slice=target,
        trainer_factory=_device_probe_trainer_factory(cfg, data, model, seen),
    )
    s.start()
    srv.wait(timeout=120)
    assert s.state == "done"
    assert s.device == target.label
    assert seen == {target.primary}, (
        f"local-train outputs on {seen}, expected {target.primary}"
    )


def test_unplaced_session_keeps_legacy_default_device():
    cfg, data, model = _cfg(comm_round=2), _data(feat=12), _model(feat=12)
    seen = set()
    srv = FederationServer()
    s = srv.create_session(
        "legacy", cfg, data, model,
        trainer_factory=_device_probe_trainer_factory(cfg, data, model, seen),
    )
    s.start()
    srv.wait(timeout=120)
    assert s.state == "done"
    assert seen == {jax.devices()[0]}


def test_server_places_tenants_and_labels_metrics_with_slice():
    slices = build_slices(2, devices_per_slice=2)
    placer = Placer(slices)
    srv = FederationServer(placer=placer)
    a = srv.create_session(
        "place_a", _cfg(comm_round=3, seed=1), _data(feat=13, seed=1),
        _model(feat=13),
    )
    # pin via the tenant's own AdminConfig (the device_slice spec key)
    b = srv.create_session(
        "place_b", _cfg(comm_round=3, seed=2, device_slice=1),
        _data(feat=14, seed=2), _model(feat=14),
    )
    assert a.device_slice is not None
    assert b.device_slice is slices[1]
    srv.start()
    srv.wait(timeout=180)
    body = srv.render_metrics()
    assert f'tenant="place_a"' in body
    # the device label carries the SLICE, not the backend kind
    a_label, b_label = a.device_slice.label, slices[1].label
    assert any(
        'tenant="place_a"' in ln and f'device="{a_label}"' in ln
        for ln in body.splitlines()
    ), body[:2000]
    assert any(
        'tenant="place_b"' in ln and f'device="{b_label}"' in ln
        for ln in body.splitlines()
    )
    # placement picture on the server
    snap = placer.snapshot()
    assert "place_b" in snap[slices[1].label]["tenants"]


def test_misconfigured_tenant_releases_its_placement():
    placer = Placer(build_slices(2))
    srv = FederationServer(placer=placer)
    with pytest.raises(ValueError):
        srv.create_session(
            "bad", _cfg(), _data(), _model(), algorithm="nope"
        )
    assert all(
        not v["tenants"] for v in placer.snapshot().values()
    ), placer.snapshot()


# ---------------------------------------------------------------------------
# supervisor escalation: restart-in-place -> re-placement
# ---------------------------------------------------------------------------


def test_supervisor_replaces_crash_looping_tenant_on_new_slice(tmp_path):
    slices = build_slices(2, devices_per_slice=1)
    placer = Placer(slices)
    srv = FederationServer(placer=placer)
    state = {"sup": None}

    def bomb(row):
        # deterministic MID-RUN crash while the tenant runs on slice 0
        # (round-completion rows carry both "round" and "t_s"; round 0's
        # completes past the build phase, so the supervisor sees a run
        # crash, not a config error) — a "sick chip": restarts in place
        # can never fix it, moving does
        sup = state["sup"]
        if (
            sup is not None
            and sup.device_slice is slices[0]
            and "t_s" in row
            and row.get("round", -1) >= 1
        ):
            raise RuntimeError("sick slice")

    sup = srv.create_session(
        "moves", _cfg(comm_round=4, device_slice=0), _data(feat=15),
        _model(feat=15),
        restart=RestartPolicy(budget=6, backoff_base_s=0.01,
                              breaker_window=2),
        checkpoint_path=str(tmp_path / "ck"), checkpoint_every=1,
        log_fn=bomb,
    )
    state["sup"] = sup
    assert sup.device_slice is slices[0]
    srv.start()
    results = srv.wait(timeout=180)
    assert results["moves"]["ok"], results
    assert sup.replacements == 1
    assert sup.device_slice is slices[1]
    assert sup.restarts >= 2  # the breaker window's crashes burned budget
    assert sup.state == "done"
    assert results["moves"]["summary"]["supervisor/replacements"] == 1
    # the /metrics device label followed the move
    body = srv.render_metrics()
    assert any(
        'tenant="moves"' in ln and f'device="{slices[1].label}"' in ln
        for ln in body.splitlines()
    )
    # placement bookkeeping moved too
    assert placer.slice_of("moves") is slices[1]


def test_supervisor_without_placer_still_quarantines_on_crash_loop(tmp_path):
    from fedml_tpu.serve import RestartBudgetExhausted

    srv = FederationServer()

    def always(row):
        # round-completion rows only ("t_s"): the crash must land mid-run
        # on every slice — a crash inside start() classifies as a config
        # error and would bypass the restart loop entirely
        if "t_s" in row and row.get("round") is not None:
            raise RuntimeError("deterministic")

    sup = srv.create_session(
        "doomed", _cfg(comm_round=4), _data(feat=16), _model(feat=16),
        restart=RestartPolicy(budget=10, backoff_base_s=0.01,
                              breaker_window=2),
        checkpoint_path=str(tmp_path / "ck2"), checkpoint_every=1,
        log_fn=always,
    )
    srv.start()
    results = srv.wait(timeout=120)
    assert not results["doomed"]["ok"]
    assert results["doomed"]["error_kind"] == "restart_exhausted"
    assert sup.replacements == 0
    assert isinstance(sup._terminal_error, RestartBudgetExhausted)
    assert sup._terminal_error.reason == "crash_loop"
