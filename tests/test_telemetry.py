"""Telemetry subsystem tests: span nesting/thread-safety, Chrome-trace
validity, Prometheus exposition (scraped and parsed in-test), comm-layer
byte/message accounting over the loopback and shm transports, the client
health registry, and the CLI --telemetry_dir end-to-end contract."""

import json
import queue
import tempfile
import threading
import time
import urllib.request

import numpy as np
import pytest

from fedml_tpu.telemetry import (
    ClientHealthRegistry,
    PrometheusExporter,
    get_comm_meter,
    get_tracer,
)
from fedml_tpu.telemetry.metrics import MetricsRegistry
from fedml_tpu.telemetry.spans import Tracer


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_records_parent_and_depth():
    tr = Tracer()
    with tr.span("round", round=0):
        with tr.span("broadcast", round=0):
            pass
        with tr.span("local_train", client=1, round=0):
            pass
    evs = {e.name: e for e in tr.events()}
    assert set(evs) == {"round", "broadcast", "local_train"}
    assert evs["broadcast"].attrs["parent"] == "round"
    assert evs["broadcast"].attrs["depth"] == 1
    assert evs["round"].attrs["depth"] == 0
    # children recorded before the parent finishes, and nested in time
    assert evs["broadcast"].ts_us >= evs["round"].ts_us
    assert evs["broadcast"].dur_us <= evs["round"].dur_us


def test_span_thread_safety_no_cross_thread_nesting():
    """N threads × M spans each: every span records, and nesting stacks are
    thread-local (no thread sees another thread's span as its parent)."""
    tr = Tracer()
    N, M = 8, 50

    def worker(tid):
        for i in range(M):
            with tr.span("outer", thread=tid, i=i):
                with tr.span("inner", thread=tid, i=i):
                    pass

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = tr.events()
    assert len(evs) == N * M * 2
    for e in evs:
        if e.name == "inner":
            assert e.attrs["parent"] == "outer"


def test_cross_thread_span_handle():
    """A round span can begin on one thread and end on another (the server
    FSM broadcast → receive-handler pattern)."""
    tr = Tracer()
    s = tr.start_span("round", round=7)
    done = threading.Event()

    def closer():
        s.end()
        done.set()

    threading.Thread(target=closer).start()
    assert done.wait(5)
    (ev,) = tr.events()
    assert ev.name == "round" and ev.attrs["round"] == 7
    assert s.end() is None  # idempotent


def test_chrome_trace_json_is_valid_and_loadable(tmp_path):
    tr = Tracer()
    with tr.span("round", round=0):
        pass
    path = str(tmp_path / "sub" / "trace.json")
    tr.write_chrome_trace(path)
    doc = json.load(open(path))
    assert "traceEvents" in doc
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    (ev,) = xs
    for key in ("name", "ts", "dur", "pid", "tid", "cat", "args"):
        assert key in ev
    assert ev["name"] == "round" and ev["args"]["round"] == 0
    # metadata events label the process and every thread
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {m["name"] for m in metas} >= {"process_name", "thread_name"}


def test_tracer_bounded_buffer_counts_drops():
    tr = Tracer(max_events=3)
    for i in range(5):
        with tr.span("s", i=i):
            pass
    assert len(tr.events()) == 3
    assert tr.dropped == 2
    assert tr.to_chrome_trace()["otherData"]["dropped_events"] == 2


def test_span_listener_sees_finished_spans_and_errors_are_contained():
    tr = Tracer()
    seen = []

    def bad_listener(ev):
        raise RuntimeError("listener bug")

    tr.add_listener(bad_listener)
    tr.add_listener(lambda ev: seen.append(ev.name))
    with tr.span("local_train", client=0, round=0):
        pass  # must not raise despite the broken listener
    assert seen == ["local_train"]


# ---------------------------------------------------------------------------
# metrics + prometheus exposition
# ---------------------------------------------------------------------------


def test_prometheus_text_exposition_scrape_and_parse():
    reg = MetricsRegistry()
    c = reg.counter("t_messages_total", "msgs", ("msg_type",))
    g = reg.gauge("t_clients_seen", "clients")
    h = reg.histogram("t_latency_seconds", "lat", buckets=(0.1, 1.0))
    c.inc(3, msg_type="s2c_sync")
    g.set(5)
    h.observe(0.05)
    h.observe(0.5)
    h.observe(7.0)
    with PrometheusExporter(port=0, registry=reg) as ex:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{ex.port}/metrics", timeout=10
        ).read().decode()
    lines = [l for l in body.splitlines() if l and not l.startswith("#")]
    parsed = {}
    for line in lines:
        name_labels, value = line.rsplit(" ", 1)
        parsed[name_labels] = float(value)
    assert parsed['t_messages_total{msg_type="s2c_sync"}'] == 3.0
    assert parsed["t_clients_seen"] == 5.0
    # cumulative buckets: 0.1 holds 1, 1.0 holds 2, +Inf holds all 3
    assert parsed['t_latency_seconds_bucket{le="0.1"}'] == 1.0
    assert parsed['t_latency_seconds_bucket{le="1.0"}'] == 2.0
    assert parsed['t_latency_seconds_bucket{le="+Inf"}'] == 3.0
    assert parsed["t_latency_seconds_count"] == 3.0
    assert abs(parsed["t_latency_seconds_sum"] - 7.55) < 1e-9
    # TYPE lines present for every family
    assert "# TYPE t_messages_total counter" in body
    assert "# TYPE t_latency_seconds histogram" in body


def test_counter_rejects_negative_and_wrong_labels():
    reg = MetricsRegistry()
    c = reg.counter("t_neg_total", "x", ("a",))
    with pytest.raises(ValueError):
        c.inc(-1, a="b")
    with pytest.raises(ValueError):
        c.inc(1, wrong="b")
    # idempotent re-registration returns the same instrument
    assert reg.counter("t_neg_total", "x", ("a",)) is c
    with pytest.raises(ValueError):
        reg.gauge("t_neg_total", "x", ("a",))


# ---------------------------------------------------------------------------
# comm accounting over real transports
# ---------------------------------------------------------------------------


def _roundtrip_message():
    """A model-carrying message with a deterministic wire size."""
    from fedml_tpu.core.message import Message

    msg = Message("s2c_sync", 0, 1)
    msg.add_params(
        "model_params", {"w": np.ones((64, 32), np.float32), "b": np.zeros(32, np.float32)}
    )
    msg.add_params("round_idx", 3)
    return msg


def _delta(before, after):
    out = {}
    for k in after:
        if not isinstance(after[k], dict):  # scalar totals (uplink_*)
            continue
        d = {
            t: after[k].get(t, 0) - before.get(k, {}).get(t, 0)
            for t in after[k]
        }
        out[k] = {t: v for t, v in d.items() if v}
    return out


def _drain_one(comm):
    """Run one receive loop until stopped; returns received messages."""
    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append(m)

    comm.add_observer(Obs())
    th = threading.Thread(target=comm.handle_receive_message, daemon=True)
    th.start()
    return got, th


def test_comm_counters_loopback():
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub

    meter = get_comm_meter()
    before = meter.snapshot()
    hub = LoopbackHub()
    a, b = LoopbackCommManager(hub, 0), LoopbackCommManager(hub, 1)
    got, th = _drain_one(b)
    msg = _roundtrip_message()
    a.send_message(msg)
    deadline = time.monotonic() + 10
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    b.stop_receive_message()
    th.join(timeout=10)
    d = _delta(before, meter.snapshot())
    assert d["messages_sent"]["s2c_sync"] == 1
    assert d["messages_received"]["s2c_sync"] == 1
    # bytes observed by the meter == the envelope's own serialized size,
    # up and down (loopback ships the exact wire image)
    assert d["bytes_sent"]["s2c_sync"] == msg.wire_size()
    assert d["bytes_received"]["s2c_sync"] == msg.wire_size()


def test_comm_counters_shm():
    from fedml_tpu.core.shm_comm import ShmCommManager

    meter = get_comm_meter()
    before = meter.snapshot()
    with tempfile.TemporaryDirectory(prefix="fedml_tel_shm_") as d:
        a = ShmCommManager(0, d)
        b = ShmCommManager(1, d)
        got, th = _drain_one(b)
        msg = _roundtrip_message()
        a.send_message(msg)
        deadline = time.monotonic() + 10
        while not got and time.monotonic() < deadline:
            time.sleep(0.01)
        b.stop_receive_message()
        th.join(timeout=10)
        a.stop_receive_message()
    dd = _delta(before, meter.snapshot())
    assert dd["messages_sent"]["s2c_sync"] == 1
    assert dd["messages_received"]["s2c_sync"] == 1
    assert dd["bytes_sent"]["s2c_sync"] == msg.wire_size()
    assert dd["bytes_received"]["s2c_sync"] == msg.wire_size()


# ---------------------------------------------------------------------------
# client health registry
# ---------------------------------------------------------------------------


def test_health_registry_participation_and_straggler_decile():
    reg = MetricsRegistry()
    h = ClientHealthRegistry(registry=reg)
    # 9 fast clients, 1 slow one, 5 rounds each
    for r in range(5):
        for cid in range(9):
            h.observe_train(cid, r, 0.1)
        h.observe_train(9, r, 2.0)
    assert h.clients_seen() == list(range(10))
    assert h.last_seen_round(9) == 4
    assert h.rounds_participated(3) == 5
    assert h.mean_train_s(9) == pytest.approx(2.0)
    assert h.straggler_ids() == [9]
    assert h.is_straggler(9) and not h.is_straggler(0)
    snap = h.snapshot()
    assert snap["9"]["straggler"] is True
    assert snap["0"]["rounds_participated"] == 5
    assert reg.get("fedml_clients_seen").value() == 10
    assert reg.get("fedml_clients_straggler_count").value() == 1


def test_health_registry_homogeneous_fleet_has_no_stragglers():
    h = ClientHealthRegistry(registry=MetricsRegistry())
    for r in range(4):
        for cid in range(8):
            # small jitter — someone is always "slowest", nobody straggles
            h.observe_train(cid, r, 0.1 + 0.001 * cid)
    assert h.straggler_ids() == []


def test_health_registry_dedupes_span_and_server_observations():
    h = ClientHealthRegistry(registry=MetricsRegistry())
    assert h.observe_train(1, 0, 0.5) is True
    # the server-side round-trip for the same (client, round) is ignored
    assert h.observe_train(1, 0, 0.9) is False
    assert h.rounds_participated(1) == 1
    assert h.mean_train_s(1) == pytest.approx(0.5)


def test_health_registry_feeds_on_local_train_spans():
    tr = Tracer()
    h = ClientHealthRegistry(registry=MetricsRegistry()).attach(tr)
    with tr.span("local_train", client=4, round=2):
        time.sleep(0.01)
    with tr.span("unrelated", client=4, round=3):
        pass
    assert h.clients_seen() == [4]
    assert h.last_seen_round(4) == 2
    assert h.mean_train_s(4) >= 0.01
    h.detach()
    with tr.span("local_train", client=5, round=0):
        pass
    assert 5 not in h.clients_seen()


# ---------------------------------------------------------------------------
# CLI end-to-end (the acceptance contract)
# ---------------------------------------------------------------------------


def test_cli_loopback_telemetry_dir_end_to_end(tmp_path):
    """3-round loopback FedAvg with --telemetry_dir: the Chrome trace parses
    and carries round/broadcast/aggregate spans for EVERY round, the health
    registry saw every client, and summary.json carries the comm totals."""
    from click.testing import CliRunner

    from fedml_tpu.cli import main

    tdir = tmp_path / "telemetry"
    ldir = tmp_path / "logs"
    result = CliRunner().invoke(
        main,
        [
            "--algorithm", "fedavg", "--runtime", "loopback",
            "--model", "lr", "--dataset", "synthetic",
            "--client_num_in_total", "4", "--client_num_per_round", "4",
            "--comm_round", "3", "--batch_size", "8",
            "--telemetry_dir", str(tdir), "--log_dir", str(ldir),
        ],
    )
    assert result.exit_code == 0, result.output
    doc = json.load(open(tdir / "trace.json"))
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    rounds_of = lambda name: sorted(
        e["args"]["round"] for e in spans if e["name"] == name
    )
    assert rounds_of("round") == [0, 1, 2]
    assert rounds_of("broadcast") == [0, 1, 2]
    assert rounds_of("aggregate") == [0, 1, 2]
    # every client trained every round (full participation), visible both
    # as local_train spans and in the health registry
    health = json.load(open(tdir / "health.json"))
    assert sorted(health) == ["0", "1", "2", "3"]
    for rec in health.values():
        assert rec["rounds_participated"] == 3
        assert rec["last_seen_round"] == 2
    summary = json.load(open(ldir / "summary.json"))
    assert summary["telemetry/comm_messages_sent"] > 0
    assert summary["telemetry/comm_bytes_sent"] > 0
    # loopback delivers exactly what was sent
    assert (
        summary["telemetry/comm_bytes_received"]
        == summary["telemetry/comm_bytes_sent"]
    )
    # the flight recorder folded every round (telemetry/flight.py): ring
    # file + flight/* summary block
    flight = json.load(open(tdir / "flight.json"))
    assert flight["rounds_folded"] >= 3
    assert [r["round"] for r in flight["records"][-3:]] == [0, 1, 2]
    assert flight["percentiles"]["round"]["p50"] > 0
    assert summary["flight/rounds_folded"] >= 3
    assert summary["flight/p50_round_s"] > 0


def test_cli_vmap_telemetry_round_spans(tmp_path):
    """The single-chip simulator runtime also records the round lifecycle
    (round/broadcast/local_train/eval) and a health registry."""
    from click.testing import CliRunner

    from fedml_tpu.cli import main

    tdir = tmp_path / "telemetry"
    get_tracer().reset()
    result = CliRunner().invoke(
        main,
        [
            "--algorithm", "fedavg", "--model", "lr",
            "--dataset", "synthetic",
            "--client_num_in_total", "4", "--client_num_per_round", "2",
            "--comm_round", "2", "--batch_size", "8",
            "--telemetry_dir", str(tdir),
        ],
    )
    assert result.exit_code == 0, result.output
    doc = json.load(open(tdir / "trace.json"))
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"round", "broadcast", "local_train", "eval"} <= names
    health = json.load(open(tdir / "health.json"))
    assert len(health) >= 2  # round-seeded sampling picked cohorts
