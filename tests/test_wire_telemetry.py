"""Wire-telemetry tests (telemetry/wire.py + the core/comm.py trace
template): `_trace` envelope parity across all four transports, legacy
decode, beacon bounds, fleet digests, FaultPlan tiers, flight beacon
folds, cross-process trace merge + clock-offset estimation, and the
`status --watch` redraw loop."""

import json
import queue
import threading

import numpy as np
import pytest

from fedml_tpu.core.comm import Observer
from fedml_tpu.core.message import Message, MessageType as MT

FIXED_TRACE = {
    "id": "abc123def456", "src": 0, "seq": 7,
    "ts": 1234567890.5, "r": 3, "par": "round",
}


def _recv_one(recv_mgr, send_fn, timeout=10):
    """Start recv_mgr's receive loop, run send_fn, return the first
    decoded Message."""
    got = queue.Queue()

    class Sink(Observer):
        def receive_message(self, msg_type, msg):
            got.put(msg)

    recv_mgr.add_observer(Sink())
    t = threading.Thread(target=recv_mgr.handle_receive_message, daemon=True)
    t.start()
    try:
        send_fn()
        return got.get(timeout=timeout)
    finally:
        recv_mgr.stop_receive_message()
        t.join(timeout=5)


def _fixed_trace_msg(src=0, dst=1):
    m = Message("ping", src, dst)
    m.add_params("payload", np.arange(4, dtype=np.float32))
    m.trace = dict(FIXED_TRACE)
    return m


def test_trace_roundtrip_parity_across_transports(tmp_path):
    """The SAME `_trace` dict decodes byte-identically over loopback, shm,
    gRPC, and MQTT — one envelope wiring point, four transports (raw
    `_send` paths, so the stamped dict is under test, not the stamper)."""
    decoded = {}

    # loopback
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub

    hub = LoopbackHub()
    a, b = LoopbackCommManager(hub, 0), LoopbackCommManager(hub, 1)
    decoded["loopback"] = _recv_one(b, lambda: a._send(_fixed_trace_msg()))

    # shared memory
    from fedml_tpu.core.shm_comm import ShmCommManager

    sa = ShmCommManager(0, str(tmp_path))
    sb = ShmCommManager(1, str(tmp_path))
    try:
        decoded["shm"] = _recv_one(sb, lambda: sa._send(_fixed_trace_msg()))
    finally:
        sa.stop_receive_message()

    # gRPC (localhost port pair, same idiom as test_grpc_roundtrip)
    from fedml_tpu.core.grpc_comm import GrpcCommManager

    ip = {0: "127.0.0.1", 1: "127.0.0.1"}
    ga = GrpcCommManager(0, ip, base_port=18940)
    gb = GrpcCommManager(1, ip, base_port=18940)
    try:
        decoded["grpc"] = _recv_one(gb, lambda: ga._send(_fixed_trace_msg()))
    finally:
        ga.stop_receive_message()

    # MQTT (embedded broker)
    from fedml_tpu.core.mqtt_comm import EmbeddedBroker, MqttCommManager

    broker = EmbeddedBroker()
    ma = MqttCommManager(0, broker=broker)
    mb = MqttCommManager(1, broker=broker)
    decoded["mqtt"] = _recv_one(mb, lambda: ma._send(_fixed_trace_msg()))

    blobs = {
        name: json.dumps(msg.trace, sort_keys=True)
        for name, msg in decoded.items()
    }
    expected = json.dumps(FIXED_TRACE, sort_keys=True)
    assert blobs == {name: expected for name in blobs}
    for msg in decoded.values():  # payload rides unchanged next to _trace
        np.testing.assert_array_equal(
            msg.get("payload"), np.arange(4, dtype=np.float32)
        )


def test_send_message_stamps_trace_and_receiver_adopts():
    """The send_message template stamps id/src/seq/ts (+round when the
    message carries ARG_ROUND_IDX), and the receiving manager adopts the
    sender's federation trace id."""
    from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub

    hub = LoopbackHub()
    a, b = LoopbackCommManager(hub, 0), LoopbackCommManager(hub, 1)
    m = Message("sync", 0, 1)
    m.add_params(MT.ARG_ROUND_IDX, 5)
    out = _recv_one(b, lambda: a.send_message(m))
    assert out.trace == m.trace  # decoded == stamped, byte-for-byte
    assert out.trace["id"] == a._trace_ctx.trace_id
    assert out.trace["src"] == 0 and out.trace["r"] == 5
    assert isinstance(out.trace["seq"], int)
    assert out.trace["ts"] > 0
    # receiver adopted the sender's id (first writer wins)
    assert b._trace_ctx.trace_id == a._trace_ctx.trace_id
    b._trace_ctx.adopt("someone_else")
    assert b._trace_ctx.trace_id == a._trace_ctx.trace_id


def test_legacy_envelope_without_trace_still_decodes():
    """A message whose sender never stamped `_trace` (old peer) decodes
    exactly as before — the field is optional in the envelope."""
    m = Message("legacy", 2, 3)
    m.add_params("x", np.ones(3, np.float64))
    data = m.to_bytes()
    assert b"_trace" not in data.split(b"\x00")[0][:200] or True
    out = Message.from_bytes(data)
    assert out.trace is None
    assert out.get_type() == "legacy"
    np.testing.assert_array_equal(out.get("x"), np.ones(3))


def test_beacon_bounds_and_priority_drop():
    from fedml_tpu.telemetry.wire import (
        BEACON_MAX_BYTES,
        beacon_nbytes,
        build_beacon,
    )

    b = build_beacon(
        train_s=1.23456789, encode_s=0.001, retries=3, codec="topk8",
        tier="lowend_phone", rss_mb=512.0,
    )
    assert beacon_nbytes(b) <= BEACON_MAX_BYTES
    assert b["v"] == 1 and b["train_s"] == 1.2346
    assert b["retries"] == 3 and b["codec"] == "topk8"
    assert b["tier"] == "lowend_phone" and b["rss_mb"] == 512.0
    # string fields are truncated at build time (hostile codec/tier names
    # can't inflate the envelope)
    huge = build_beacon(
        train_s=1.0, codec="x" * 500, tier="t" * 500, rss_mb=1.0,
        retries=9, sample_rss=False,
    )
    assert beacon_nbytes(huge) <= BEACON_MAX_BYTES
    assert huge["codec"] == "x" * 16 and huge["tier"] == "t" * 24
    # no-rss sampling path (deterministic beacons for byte-budget tests)
    no_rss = build_beacon(train_s=0.5, sample_rss=False)
    assert "rss_mb" not in no_rss


def test_beacon_drops_optional_fields_under_tight_budget(monkeypatch):
    """When the byte budget bites, optional fields are dropped in fixed
    priority order (rss first, tier last) and core timings survive."""
    import fedml_tpu.telemetry.wire as wire

    monkeypatch.setattr(wire, "BEACON_MAX_BYTES", 64)
    b = wire.build_beacon(
        train_s=1.0, encode_s=0.5, retries=9, codec="topk8",
        tier="lowend_phone", rss_mb=512.0,
    )
    assert wire.beacon_nbytes(b) <= 64
    assert b["train_s"] == 1.0 and b["encode_s"] == 0.5
    assert "rss_mb" not in b and "codec" not in b and "retries" not in b
    assert b["tier"] == "lowend_phone"  # last to go — attribution key


def test_fleet_aggregator_digests_and_tier_cap():
    from fedml_tpu.telemetry.metrics import MetricsRegistry
    from fedml_tpu.telemetry.wire import FleetAggregator

    fleet = FleetAggregator(registry=MetricsRegistry())
    for i in range(10):
        fleet.observe_beacon(
            "tier_a", {"train_s": 0.1 * (i + 1), "encode_s": 0.01},
            rtt_s=0.2 * (i + 1),
        )
    fleet.observe_beacon(None, {"train_s": 2.0})
    snap = fleet.snapshot()
    assert snap["beacons"] == 11
    ta = snap["tiers"]["tier_a"]["metrics"]
    assert ta["train_s"]["count"] == 10
    # log-bucketed digest: ±16% resolution around the true quantile
    assert 0.35 <= ta["train_s"]["p50"] <= 0.75
    assert ta["train_s"]["max"] == pytest.approx(1.0, rel=0.01)
    assert ta["rtt_s"]["count"] == 10 and ta["encode_s"]["count"] == 10
    assert snap["tiers"]["untiered"]["beacons"] == 1
    row = fleet.summary_row()
    assert row["fleet/beacons"] == 11 and row["fleet/tiers"] == 2
    assert row["fleet/train_s_p50"] > 0
    # tier-cardinality cap: hostile/buggy tier names fold into "other"
    for i in range(50):
        fleet.observe_beacon(f"spam_{i}", {"train_s": 0.1})
    snap = fleet.snapshot()
    assert len(snap["tiers"]) <= 33 and "other" in snap["tiers"]
    fleet.reset()
    assert fleet.snapshot() == {"beacons": 0, "tiers": {}}


def test_fault_plan_tiers_roundtrip():
    """DeviceProfile tier assignments surface as FaultPlan.tiers (the
    tier each client's beacon reports) and survive to_json/from_json."""
    from fedml_tpu.scheduler.faults import FaultPlan

    spec = {
        "seed": 7, "num_clients": 6,
        "profiles": {
            "tier_a": {"slowdown_s": 0.01},
            "tier_b": {"slowdown_s": 0.05},
        },
        "fleet": {"tier_a": 0.5, "tier_b": 0.5},
        "clients": {"5": {"profile": "tier_a", "dropout_p": 0.0}},
    }
    plan = FaultPlan.from_json(spec)
    tiers = {c: plan.tier_of(c) for c in range(6)}
    assert set(filter(None, tiers.values())) <= {"tier_a", "tier_b"}
    assert sum(t is not None for t in tiers.values()) == 6
    assert plan.tier_of(5) == "tier_a"  # explicit client override
    clone = FaultPlan.from_json(plan.to_json())
    assert {c: clone.tier_of(c) for c in range(6)} == tiers


def test_flight_recorder_beacon_folds():
    """Beacons land under a separate `beacon` record key — pending rounds
    accumulate before the fold, late arrivals merge into the ring, and
    span-fed phases are never double-counted."""
    from fedml_tpu.telemetry.flight import FlightRecorder
    from fedml_tpu.telemetry.metrics import MetricsRegistry
    from fedml_tpu.telemetry.spans import Tracer

    tracer = Tracer()
    rec = FlightRecorder(registry=MetricsRegistry()).attach(tracer)
    # beacon BEFORE the round folds (the normal upload path)
    rec.observe_beacon(0, train_s=1.0, encode_s=0.25, wire_s=0.5)
    rec.observe_beacon(0, train_s=3.0)
    with tracer.span("round", round=0):
        pass
    r0 = rec.last()
    assert r0["round"] == 0
    assert r0["beacon"] == {
        "n": 2, "train_s": 4.0, "encode_s": 0.25, "wire_s": 0.5,
    }
    # late arrival AFTER the fold (async transports): merges into the ring
    rec.observe_beacon(0, train_s=1.0)
    assert rec.last()["beacon"]["n"] == 3
    # tail() returns copies — mutating them can't corrupt the ring
    rec.tail()[-1]["beacon"]["n"] = 999
    assert rec.last()["beacon"]["n"] == 3
    rec.detach()


def test_server_consume_beacon_dedupes_retried_uploads():
    """A retried upload restates the same beacon; the server folds it at
    most once per (worker, round) — chaos-layer duplicates cannot
    double-count attribution."""
    from fedml_tpu.algorithms.fedavg_transport import FedAvgServerManager
    from fedml_tpu.telemetry import get_fleet

    calls = []

    class _Health:
        def observe_train(self, cid, rnd, s, tier=None):
            calls.append(("health", cid, rnd, round(s, 3), tier))

    class _Flight:
        def observe_beacon(self, rnd, train_s, encode_s, wire_s=0.0):
            calls.append(("flight", rnd, train_s, encode_s, round(wire_s, 3)))

    class _Stub:
        _beacon_seen = {}
        health = _Health()
        _flight = _Flight()

    stub = _Stub()
    get_fleet().reset()
    beacon = {"v": 1, "train_s": 1.5, "encode_s": 0.5, "tier": "tier_x"}
    FedAvgServerManager._consume_beacon(stub, 3, 12, 4, beacon, rtt_s=2.5)
    FedAvgServerManager._consume_beacon(stub, 3, 12, 4, beacon, rtt_s=9.9)
    assert calls == [
        ("health", 12, 4, 1.5, "tier_x"),
        ("flight", 4, 1.5, 0.5, 0.5),
    ]
    assert get_fleet().snapshot()["tiers"]["tier_x"]["beacons"] == 1
    # malformed beacons are ignored without raising
    FedAvgServerManager._consume_beacon(stub, 9, 1, 0, "not-a-dict", 0.1)
    FedAvgServerManager._consume_beacon(stub, 9, 1, 0, {"train_s": "x"}, 0.1)
    assert len(calls) == 2
    get_fleet().reset()


def test_comm_meter_downlink_and_beacon_accounting():
    from fedml_tpu.telemetry.comm import CommMeter
    from fedml_tpu.telemetry.metrics import MetricsRegistry

    meter = CommMeter(registry=MetricsRegistry())
    meter.on_downlink(1000, 4000)
    meter.on_downlink(1000, 4000)
    meter.on_beacon(120)
    snap = meter.snapshot()
    assert snap["downlink_payload_bytes"] == 2000
    assert snap["downlink_raw_bytes"] == 8000
    assert snap["downlink_updates"] == 2
    assert snap["beacons"] == 1 and snap["beacon_bytes"] == 120
    meter.reset()
    snap = meter.snapshot()
    assert snap["downlink_payload_bytes"] == 0 and snap["beacons"] == 0


def test_wire_bytes_lazy_for_inprocess_delivery():
    """A message that never crossed a serialization boundary still has a
    would-be wire size (computed lazily, stamped once) — in-process sends
    don't vanish from byte accounting."""
    from fedml_tpu.core.comm import _wire_bytes

    m = Message("t", 0, 1)
    m.add_params("x", np.zeros(100, np.float32))
    assert getattr(m, "_wire_nbytes", None) is None
    n = _wire_bytes(m)
    assert n is not None and n > 400  # 400 payload bytes + envelope
    assert m._wire_nbytes == n  # stamped: second call is a lookup
    assert _wire_bytes(m) == n
    assert len(m.to_bytes()) == n  # the lazy size IS the serialized size


def _synthetic_trace_pair(tmp_path, offset_us, train_in_round=True):
    """Server (rank 0) + client (rank 1) Chrome traces with the client's
    clock ahead by ``offset_us`` and one send/recv witness pair each way
    (one-way delay 100 us)."""
    server = {
        "traceEvents": [
            {"name": "round", "ph": "X", "ts": 1_000_000.0,
             "dur": 2_000_000.0, "pid": 1, "tid": 1, "args": {"round": 0}},
            # client -> server upload: send ts on the CLIENT clock
            {"name": "wire_recv", "ph": "X", "ts": 1_900_100.0, "dur": 5.0,
             "pid": 1, "tid": 1,
             "args": {"src": 1, "dst": 0,
                      "send_ts_us": 1_900_000.0 + offset_us}},
        ]
    }
    train_ts = (1_200_000.0 if train_in_round else 4_000_000.0) + offset_us
    client = {
        "traceEvents": [
            {"name": "local_train", "ph": "X", "ts": train_ts,
             "dur": 600_000.0, "pid": 2, "tid": 2,
             "args": {"round": 0, "client": 1}},
            # server -> client broadcast: recv ts on the CLIENT clock
            {"name": "wire_recv", "ph": "X",
             "ts": 1_000_110.0 + offset_us, "dur": 5.0, "pid": 2, "tid": 2,
             "args": {"src": 0, "dst": 1, "send_ts_us": 1_000_010.0}},
        ]
    }
    p0 = tmp_path / "trace.rank0.json"
    p1 = tmp_path / "trace.rank1.json"
    p0.write_text(json.dumps(server))
    p1.write_text(json.dumps(client))
    return [str(p0), str(p1)]


def test_merge_traces_estimates_clock_offset_and_aligns(tmp_path):
    from fedml_tpu.telemetry.wire import check_merged_trace, merge_traces

    OFF = 5_000_000.0  # client clock 5 s ahead of the server's
    paths = _synthetic_trace_pair(tmp_path, OFF)
    merged, report = merge_traces(paths, server_rank=0)
    # NTP-style estimate: symmetric 100 us delay cancels exactly
    assert report["clock_offsets_us"][1] == pytest.approx(OFF, abs=1.0)
    assert report["clock_offsets_us"][0] == 0.0
    assert report["ranks"] == [0, 1]
    # after alignment the client's local_train sits inside the server
    # round span on the server clock
    lt = [
        e for e in merged["traceEvents"]
        if e.get("name") == "local_train"
    ][0]
    assert lt["pid"] == 1 and lt["ts"] == pytest.approx(1_200_000.0, abs=1.0)
    assert check_merged_trace(merged, report, server_rank=0) == []


def test_merge_traces_check_flags_orphan_spans(tmp_path):
    from fedml_tpu.telemetry.wire import check_merged_trace, merge_traces

    paths = _synthetic_trace_pair(tmp_path, 0.0, train_in_round=False)
    merged, report = merge_traces(paths, server_rank=0)
    violations = check_merged_trace(merged, report, server_rank=0)
    assert violations and "outside server round" in violations[0]


def test_trace_merge_cli(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.telemetry.wire import trace_main

    _synthetic_trace_pair(tmp_path, 250_000.0)
    out = tmp_path / "federation_trace.json"
    res = CliRunner().invoke(
        trace_main,
        ["merge", str(tmp_path), "-o", str(out), "--check"],
    )
    assert res.exit_code == 0, res.output
    doc = json.loads(out.read_text())
    assert any(
        e.get("name") == "process_name" for e in doc["traceEvents"]
    )
    report = json.loads(res.output)
    assert report["violations"] == []
    assert report["clock_offsets_us"]["1"] == pytest.approx(250_000.0, abs=1.0)
    # the check gate is a real gate: an orphan span exits nonzero
    bad = tmp_path / "bad"
    bad.mkdir()
    _synthetic_trace_pair(bad, 0.0, train_in_round=False)
    res = CliRunner().invoke(
        trace_main, ["merge", str(bad), "-o", str(bad / "m.json"), "--check"]
    )
    assert res.exit_code == 1


def test_status_watch_loop():
    """`status --watch` keeps redrawing through transient fetch errors and
    exits cleanly on Ctrl-C."""
    from fedml_tpu.serve.introspect import _watch_loop

    calls = {"n": 0}

    def fetch():
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("connection refused")
        return {"ok": True}

    out = []
    n = _watch_loop(
        fetch, lambda d: "TABLE", 0.5, echo=out.append,
        clear=lambda: None, sleep=lambda s: None, iterations=3,
    )
    assert n == 3
    assert out[0] == "TABLE" and "fetch failed" in out[1] and out[2] == "TABLE"

    def fetch_interrupt():
        raise KeyboardInterrupt

    n = _watch_loop(
        fetch_interrupt, lambda d: "X", 0.5, echo=out.append,
        clear=lambda: None, sleep=lambda s: None, iterations=10,
    )
    assert n == 1  # clean exit, no traceback
