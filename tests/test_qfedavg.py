"""q-FedAvg fair aggregation (algorithms/qfedavg.py) — beyond the
reference's inventory (no fairness-aware aggregation anywhere in
SURVEY §2b)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.algorithms.qfedavg import QFedAvgAPI, qfedavg_update
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model


def _cfg(rounds=3, per_round=4, total=8, lr=0.1):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=total, client_num_per_round=per_round,
            comm_round=rounds, epochs=1, frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=lr),
        seed=0,
    )


def _data_model(**kw):
    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(6,), samples_per_client=16,
        partition_method="homo", ragged=False, seed=0, **kw,
    )
    return data, create_model("lr", "synthetic", (6,), 3)


def test_q_zero_equals_uniform_mean():
    """Degenerate-config oracle: q=0 reduces q-FedAvg to the uniform mean
    of the client models (Delta_k = g_k, h_k = 1/lr)."""
    key = jax.random.PRNGKey(0)
    gv = {"w": jax.random.normal(key, (4, 3)), "b": jnp.zeros((3,))}
    cvs = jax.tree_util.tree_map(
        lambda g: jnp.stack(
            [g + 0.1 * jax.random.normal(jax.random.fold_in(key, i), g.shape)
             for i in range(5)]
        ),
        gv,
    )
    losses = jnp.asarray([0.5, 2.0, 1.0, 0.1, 3.0])
    out = qfedavg_update(gv, cvs, losses, lr=0.1, q=0.0)
    mean = jax.tree_util.tree_map(lambda s: jnp.mean(s, axis=0), cvs)
    for a, b in zip(
        jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(mean)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_q_upweights_high_loss_clients():
    """q>0 pulls the update toward the high-loss client's direction."""
    gv = {"w": jnp.zeros((6,))}
    lo = {"w": jnp.ones((6,)) * 0.1}    # low-loss client's model
    hi = {"w": -jnp.ones((6,)) * 0.1}   # high-loss client's model
    cvs = jax.tree_util.tree_map(lambda a, b: jnp.stack([a, b]), lo, hi)
    losses = jnp.asarray([0.1, 5.0])
    out0 = qfedavg_update(gv, cvs, losses, lr=0.1, q=0.0)["w"]
    out2 = qfedavg_update(gv, cvs, losses, lr=0.1, q=2.0)["w"]
    # q=0: exact midpoint (zero); q=2: dominated by the high-loss client
    np.testing.assert_allclose(np.asarray(out0), 0.0, atol=1e-6)
    assert float(out2[0]) < -0.05  # pulled toward hi's -0.1


def test_qfedavg_round_q0_matches_fedavg_uniform():
    """Full-round oracle on equal shard sizes: QFedAvgAPI at q=0 ==
    FedAvgAPI (whose sample weights are uniform when shards are equal)."""
    data, model = _data_model()
    qa = QFedAvgAPI(_cfg(), data, model, q=0.0)
    fa = FedAvgAPI(_cfg(), data, model)
    for r in range(3):
        qa.train_round(r)
        fa.train_round(r)
    for a, b in zip(
        jax.tree_util.tree_leaves(qa.global_vars),
        jax.tree_util.tree_leaves(fa.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )


def test_qfedavg_learns_and_rejects_momentum():
    data, model = _data_model()
    api = QFedAvgAPI(_cfg(rounds=20, per_round=8), data, model, q=1.0)
    for r in range(20):
        api.train_round(r)
    _, acc = api.evaluate_global()
    assert acc > 0.8, f"q-FedAvg failed to learn: {acc}"
    with pytest.raises(ValueError):
        QFedAvgAPI(
            RunConfig(
                data=DataConfig(batch_size=8),
                fed=FedConfig(client_num_in_total=4, client_num_per_round=2),
                train=TrainConfig(client_optimizer="sgd", momentum=0.9),
            ),
            data, model, q=1.0,
        )


def test_cli_qfedavg_reachable():
    import json

    from click.testing import CliRunner

    from fedml_tpu.cli import main

    result = CliRunner().invoke(
        main,
        [
            "--algorithm", "qfedavg", "--dataset", "synthetic",
            "--model", "lr", "--client_num_in_total", "8",
            "--client_num_per_round", "4", "--comm_round", "2",
            "--batch_size", "8", "--lr", "0.1", "--qffl_q", "1.0",
        ],
    )
    assert result.exit_code == 0, result.output
    row = json.loads(result.output.strip().splitlines()[-1])
    assert "Test/Acc" in row
