"""Client scheduling & fault-injection runtime (fedml_tpu/scheduler/).

Contracts pinned here:

- policy determinism: every policy is a pure function of (seed, round,
  context) — two fresh schedulers (a "restart") select identically.
- uniform parity: the ``uniform`` policy IS the reference draw
  (np.random.seed(round) + choice), and the ``client_sampling`` shim
  still delegates to it.
- power-of-choice bias: high-loss clients are over-selected.
- straggler_aware avoidance: telemetry-flagged stragglers are skipped
  while enough fast clients exist.
- sim/transport parity: the vmap simulator and the loopback federation
  select byte-identical per-round cohorts from one config.
- fault-injected quorum rounds complete with the partial cohort
  aggregated at correct sample weights, and the dropout lands in the
  health registry.
- scheduler state survives the checkpoint round-trip, so a resumed run
  re-selects its in-flight cohort.
"""

import json

import jax
import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.scheduler import (
    ClientScheduler,
    FaultInjector,
    FaultPlan,
    SelectionContext,
    get_policy,
    make_policy,
    overprovisioned_k,
    select_clients,
)
from fedml_tpu.telemetry import ClientHealthRegistry


def _data(num_clients=6, samples=12):
    return synthetic_classification(
        num_clients=num_clients, num_classes=3, feat_shape=(5,),
        samples_per_client=samples, partition_method="homo", seed=9,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(5,),
        num_classes=3, name="lr",
    )


def _cfg(**fed_kw):
    base = dict(
        client_num_in_total=6, client_num_per_round=3, comm_round=3,
        epochs=1, frequency_of_the_test=1,
    )
    base.update(fed_kw)
    return RunConfig(
        data=DataConfig(batch_size=-1),
        fed=FedConfig(**base),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


# ---------------------------------------------------------------------------
# policies
# ---------------------------------------------------------------------------


def test_uniform_policy_reference_parity():
    np.random.seed(7)
    expect = np.random.choice(range(50), 10, replace=False)
    got = select_clients(7, 50, 10, policy="uniform")
    assert np.array_equal(got, expect)
    # the back-compat shim delegates to the same draw
    from fedml_tpu.algorithms.fedavg import client_sampling

    assert np.array_equal(client_sampling(7, 50, 10), expect)
    assert np.array_equal(client_sampling(0, 5, 5), np.arange(5))
    with pytest.raises(ValueError):
        client_sampling(0, 4, 5)


@pytest.mark.parametrize(
    "policy", ["uniform", "weighted", "power_of_choice", "straggler_aware"]
)
def test_policy_determinism_across_restarts(policy):
    """A 'restart' (fresh scheduler, same seed/config/fed state) selects
    the same cohorts for every round."""
    counts = np.arange(1, 13) * 4

    def run():
        s = ClientScheduler(
            num_clients=12, k=4, policy=policy, seed=5, sample_counts=counts
        )
        for r in range(6):
            s.report_loss(r, 1.0 + r)  # same feed on both "runs"
        return [s.select(r).tolist() for r in range(8)]

    assert run() == run()


def test_seed_changes_non_uniform_policies():
    counts = np.arange(1, 13) * 4
    a = ClientScheduler(num_clients=12, k=4, policy="weighted", seed=0,
                        sample_counts=counts)
    b = ClientScheduler(num_clients=12, k=4, policy="weighted", seed=1,
                        sample_counts=counts)
    sels_a = [a.select(r).tolist() for r in range(8)]
    sels_b = [b.select(r).tolist() for r in range(8)]
    assert sels_a != sels_b  # seed participates in the draw


def test_weighted_policy_biases_to_large_shards():
    counts = np.ones(20)
    counts[:4] = 100.0  # clients 0-3 hold almost all the data
    ctx = SelectionContext(seed=0, num_clients=20, sample_counts=counts)
    pol = get_policy("weighted")
    hits = np.zeros(20)
    for r in range(200):
        hits[pol.select(r, 4, ctx)] += 1
    assert hits[:4].mean() > 4 * max(hits[4:].mean(), 1.0)


def test_power_of_choice_overselects_high_loss_clients():
    losses = {i: (10.0 if i < 4 else 0.1) for i in range(20)}
    ctx = SelectionContext(seed=0, num_clients=20, losses=losses)
    pol = get_policy("power_of_choice")
    hits = np.zeros(20)
    rounds = 200
    for r in range(rounds):
        sel = pol.select(r, 4, ctx)
        assert len(set(sel.tolist())) == 4
        hits[sel] += 1
    # whenever a high-loss client lands in the candidate set it wins a
    # slot; low-loss clients only fill leftovers
    assert hits[:4].min() > 2 * hits[4:].mean()


def test_power_of_choice_explores_unknown_clients_first():
    # clients with NO reported loss rank as +inf: both must be selected
    losses = {i: 1.0 for i in range(10) if i not in (3, 7)}
    ctx = SelectionContext(seed=0, num_clients=10, losses=losses)
    pol = get_policy("power_of_choice", candidate_factor=10.0)  # all candidates
    sel = set(pol.select(0, 2, ctx).tolist())
    assert sel == {3, 7}


def test_straggler_aware_avoids_flagged_clients():
    reg = ClientHealthRegistry()
    for r in range(8):
        for cid in range(10):
            reg.observe_train(cid, r, 10.0 if cid == 9 else 0.1)
    assert reg.straggler_ids() == [9]
    ctx = SelectionContext(seed=0, num_clients=10, health=reg)
    pol = get_policy("straggler_aware")
    for r in range(30):
        assert 9 not in pol.select(r, 4, ctx)
    # but participation wins when there are not enough fast clients:
    # k=10 of 10 must still include the straggler
    assert 9 in pol.select(0, 10, ctx)


def test_overprovision_wraps_any_policy():
    assert overprovisioned_k(4, 1.5, 100) == 6
    assert overprovisioned_k(4, 1.5, 5) == 5  # clamped to the population
    pol = make_policy("uniform", overprovision_factor=1.5)
    ctx = SelectionContext(seed=0, num_clients=100)
    sel = pol.select(0, 4, ctx)
    assert len(sel) == 6 and len(set(sel.tolist())) == 6
    # parity: the wrapper is exactly the inner policy at ceil(k*factor)
    np.random.seed(0)
    assert np.array_equal(sel, np.random.choice(range(100), 6, replace=False))


def test_unknown_policy_raises():
    with pytest.raises(ValueError, match="unknown selection policy"):
        get_policy("nope")


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------


def test_fault_plan_parse_and_determinism():
    spec = json.dumps(
        {
            "seed": 3,
            "default": {"flaky_upload_p": 0.25},
            "clients": {
                "2": {"dropout_p": 0.5, "slowdown_s": 0.1},
                "4": {"crash_at_round": 2},
            },
        }
    )
    a, b = FaultPlan.from_spec(spec), FaultPlan.from_spec(spec)
    for cid in range(6):
        for r in range(10):
            assert a.decide(cid, r) == b.decide(cid, r)
    assert a.has_participation_faults()
    assert not a.decide(4, 1).crashed and a.decide(4, 2).crashed
    assert a.decide(4, 7).crashed  # permanent from crash_at_round on
    # dropout_p=0.5 actually fires sometimes and not always
    drops = [a.decide(2, r).drop for r in range(50)]
    assert any(drops) and not all(drops)
    assert a.decide(2, 0).slowdown_s == 0.1
    # round-trip through to_json
    c = FaultPlan.from_json(a.to_json())
    assert c.decide(2, 13) == a.decide(2, 13)


def test_fault_plan_rejects_malformed():
    with pytest.raises(ValueError, match="not valid JSON"):
        FaultPlan.from_spec("{bad json")
    with pytest.raises(ValueError, match="unknown fault spec keys"):
        FaultPlan.from_spec('{"clients": {"0": {"dropout": 1}}}')
    with pytest.raises(ValueError, match="dropout_p"):
        FaultPlan.from_spec('{"default": {"dropout_p": 1.5}}')
    assert FaultPlan.from_spec("") is None
    assert FaultPlan.from_spec(None) is None


def test_fault_plan_from_file(tmp_path):
    p = tmp_path / "plan.json"
    p.write_text('{"clients": {"1": {"dropout_p": 1.0}}}')
    plan = FaultPlan.from_spec(str(p))
    assert plan.decide(1, 0).drop and not plan.decide(0, 0).drop


# ---------------------------------------------------------------------------
# simulator wiring
# ---------------------------------------------------------------------------


def test_sim_fault_filtering_and_summary(tmp_path):
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    data, model = _data(), _model()
    cfg = _cfg(fault_plan='{"seed": 1, "clients": {"1": {"crash_at_round": 0}}}')
    rows = []
    api = FedAvgAPI(cfg, data, model, log_fn=rows.append)
    api.train()
    # client 1 never trains: removed from every cohort it was selected for
    for r in range(cfg.fed.comm_round):
        assert 1 not in api._round_plan(r)[0]
    sel_rows = [r for r in rows if "scheduler/selected" in r]
    assert len(sel_rows) == cfg.fed.comm_round
    assert api.faults.counters["crash"] == 1  # one event, not one per round
    assert api.health.faults(1).get("crash") == 1


def test_sim_round_plan_memoizes_fault_decisions():
    data, model = _data(), _model()
    cfg = _cfg(fault_plan='{"seed": 1, "clients": {"2": {"dropout_p": 1.0}}}')
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    api = FedAvgAPI(cfg, data, model)
    a = api._sample_clients(0)
    b = api._sample_clients(0)  # hierarchical-style direct re-derivation
    assert np.array_equal(a, b)
    assert 2 in api.scheduler.select(0).tolist()  # selected...
    assert 2 not in a.tolist()  # ...then dropped by the plan
    # the dropped client was counted ONCE despite two derivations
    assert api.faults.counters["dropout"] == 1


def test_participation_faults_disable_fused_chunks():
    """Rounds shrunk by faults have ragged client-axis sizes — the fused
    multi-round stack would crash on them, so the chunk planner must fall
    back to eager rounds whenever the plan can drop."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    data, model = _data(samples=16), _model()

    def mk(fault_plan=""):
        cfg = RunConfig(
            data=DataConfig(batch_size=8),
            fed=FedConfig(
                client_num_in_total=6, client_num_per_round=3, comm_round=6,
                epochs=1, frequency_of_the_test=6, fused_rounds=4,
                fault_plan=fault_plan,
            ),
            train=TrainConfig(client_optimizer="sgd", lr=0.1),
            seed=0,
        )
        return FedAvgAPI(cfg, data, model)

    faulty = mk('{"clients": {"1": {"dropout_p": 1.0}}}')
    assert faulty._fused_chunk_len(1) == 1
    # slowdown-only plans have no participation faults — fusion stays on
    slow = mk('{"default": {"slowdown_s": 0.5}}')
    if slow._store is not None:  # device store required for fusion at all
        assert slow._fused_chunk_len(1) > 1


def test_fedbuff_fault_starvation_raises_instead_of_hanging():
    """A plan that crashes every client must terminate the async run with
    a loud error (decline/re-dispatch would otherwise spin forever with
    the buffer never reaching async_buffer_k)."""
    from fedml_tpu.algorithms.fedbuff import run_fedbuff_loopback

    data, model = _data(), _model()
    cfg = _cfg(
        comm_round=4, async_buffer_k=2, frequency_of_the_test=10,
        fault_plan='{"default": {"crash_at_round": 0}}',
    )
    with pytest.raises(RuntimeError, match="starved"):
        run_fedbuff_loopback(cfg, data, model)


# ---------------------------------------------------------------------------
# sim/transport parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "policy,factor",
    [("uniform", 1.0), ("weighted", 1.5), ("power_of_choice", 1.0)],
)
@pytest.mark.recompile_budget(60)  # standalone worst case ~50 across all
# three params; a cache-key instability recompiling per round would not fit
def test_selection_parity_simulation_vs_transport(
    policy, factor, recompile_sentinel
):
    """Same seed + config ⇒ byte-identical per-round selected-client sets
    in the vmap simulator and the loopback transport federation.

    power_of_choice parity is the PR 4 scheduler follow-up: the vmap round
    program now returns per-client loss vectors, so the simulator biases
    on TRUE per-client losses (not the cohort mean) — the same signal the
    transport reads off its uploads' ARG_TRAIN_LOSS."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation

    data, model = _data(), _model()
    cfg = _cfg(selection=policy, overprovision_factor=factor)
    api = FedAvgAPI(cfg, data, model)
    api.train()
    sim_sel = api.scheduler.selections()

    server = run_loopback_federation(cfg, data, model)
    tr_sel = server.scheduler.selections()
    assert sim_sel == tr_sel
    # overprovisioning actually grew the cohort (and the worker fleet)
    expect_k = overprovisioned_k(
        cfg.fed.client_num_per_round, factor, cfg.fed.client_num_in_total
    )
    assert all(len(v) == expect_k for v in sim_sel.values())
    assert server.worker_num == expect_k


# ---------------------------------------------------------------------------
# fault-injected quorum round (transport)
# ---------------------------------------------------------------------------


def test_fault_injected_quorum_round_aggregates_partial_set():
    """A dropout-injected deadline round completes via the quorum path
    with NO hang, aggregates exactly the survivors at their sample
    weights, and records the dropout in telemetry health."""
    from fedml_tpu.algorithms.fedavg import weighted_average
    from fedml_tpu.algorithms.fedavg_transport import (
        LocalTrainer,
        run_loopback_federation,
    )

    data, model = _data(num_clients=3), _model()
    # min_clients=2 pins the quorum to BOTH survivors: the round closes
    # deterministically on their two uploads (never on a compile-delayed
    # single upload racing the deadline timer)
    cfg = _cfg(
        client_num_in_total=3, client_num_per_round=3, comm_round=1,
        deadline_s=1.0, min_clients=2,
        fault_plan='{"seed": 1, "clients": {"%d": {"dropout_p": 1.0}}}'
        % 0,
    )
    rows = []
    server = run_loopback_federation(cfg, data, model, log_fn=rows.append)
    # round 0 samples all 3 clients; client 0 drops — expected model is the
    # weighted average of ONLY clients 1 and 2's local results
    import jax.numpy as jnp

    w0 = jax.device_get(
        model.init(jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 0))
    )
    locals_ = []
    ns = []
    for cid in (1, 2):
        t = LocalTrainer(cfg, data, model, "classification")
        t.update_dataset(cid)
        w, n = t._train(0, w0)
        locals_.append(w)
        ns.append(float(n))
    stacked = jax.tree_util.tree_map(
        lambda *ls: jnp.stack([jnp.asarray(l) for l in ls]), *locals_
    )
    expect = jax.device_get(
        weighted_average(stacked, jnp.asarray(ns, jnp.float32))
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(server.global_vars),
        jax.tree_util.tree_leaves(expect),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    assert server.health.faults(0).get("dropout") == 1
    faults_row = [r for r in rows if "faults/dropouts" in r]
    assert faults_row and faults_row[-1]["faults/dropouts"] == 1


def test_all_dropped_sync_round_abandons_instead_of_hanging():
    """When the ENTIRE cohort drops, no upload can ever close the round —
    after three barren deadlines the server abandons it with the model
    unchanged and moves on (a wedged federation is worse than a violated
    quorum floor)."""
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation

    data, model = _data(num_clients=3), _model()
    cfg = _cfg(
        client_num_in_total=3, client_num_per_round=3, comm_round=2,
        deadline_s=0.3, min_clients=2,
        fault_plan='{"default": {"dropout_p": 1.0}}',
    )
    server = run_loopback_federation(cfg, data, model)
    assert [r["round"] for r in server.history] == [0, 1]
    assert server.abandoned_rounds == 2


def test_zero_weight_shards_do_not_crash_weighted_policies():
    """A zero-sample client shard (possible under the Dirichlet
    partitioner) must not crash the p-weighted draws when the request
    exceeds the non-zero support."""
    counts = np.array([0, 0, 5, 5, 0, 3])
    ctx = SelectionContext(seed=0, num_clients=6, sample_counts=counts)
    sel = get_policy("weighted").select(0, 5, ctx)
    assert len(set(sel.tolist())) == 5
    sel2 = get_policy("power_of_choice").select(0, 4, ctx)
    assert len(set(sel2.tolist())) == 4
    # the weighted mass is still honored: non-zero shards always included
    assert {2, 3, 5} <= set(sel.tolist())


def test_participation_faults_without_deadline_rejected():
    from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation

    data, model = _data(num_clients=3), _model()
    cfg = _cfg(
        client_num_in_total=3, client_num_per_round=3, comm_round=1,
        fault_plan='{"clients": {"0": {"dropout_p": 1.0}}}',
    )
    with pytest.raises(ValueError, match="deadline_s"):
        run_loopback_federation(cfg, data, model)


# ---------------------------------------------------------------------------
# checkpoint round-trip
# ---------------------------------------------------------------------------


def test_scheduler_state_checkpoint_roundtrip(tmp_path):
    from fedml_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    s = ClientScheduler(num_clients=20, k=4, policy="power_of_choice", seed=2)
    for cid in range(10):
        s.report_loss(cid, float(cid))
    first = [s.select(r).tolist() for r in range(4)]

    p = str(tmp_path / "ckpt")
    save_checkpoint(
        p, {"params": {"w": np.zeros(3, np.float32)}}, round_idx=4,
        sched_state=s.state_dict(),
    )
    _, round_idx, _, _, _, sched_state = load_checkpoint(p)
    assert round_idx == 4 and sched_state is not None

    resumed = ClientScheduler(
        num_clients=20, k=4, policy="power_of_choice", seed=2
    )
    resumed.load_state_dict(sched_state)
    # in-flight rounds re-select identically (memo) and the restored loss
    # map makes FUTURE rounds identical to the uninterrupted stream too
    assert [resumed.select(r).tolist() for r in range(4)] == first
    s.report_loss(3, 99.0)
    resumed.report_loss(3, 99.0)
    assert resumed.select(4).tolist() == s.select(4).tolist()


def test_checkpoint_without_sched_state_loads_none(tmp_path):
    from fedml_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    p = str(tmp_path / "ckpt")
    save_checkpoint(p, {"params": {"w": np.zeros(2, np.float32)}}, round_idx=1)
    out = load_checkpoint(p)
    assert len(out) == 6 and out[5] is None


# ---------------------------------------------------------------------------
# fault injector accounting
# ---------------------------------------------------------------------------


def test_fault_injector_summary_row_and_crash_dedupe():
    plan = FaultPlan.from_spec('{"clients": {"0": {"crash_at_round": 0}}}')
    reg = ClientHealthRegistry()
    inj = FaultInjector(plan, health=reg)
    for r in range(5):
        inj.record(0, r, "crash")
    inj.record(1, 0, "dropout")
    row = inj.summary_row()
    assert row["faults/crashes"] == 1  # one crash event per client
    assert row["faults/dropouts"] == 1
    assert row["faults/total"] == 2
    assert reg.faults(0) == {"crash": 1}
    assert reg.snapshot()["1"]["faults"] == {"dropout": 1}
