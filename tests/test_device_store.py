"""Device-resident data store + mixed-precision policy tests."""

import numpy as np

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.base import stack_clients
from fedml_tpu.data.device_store import DeviceDataStore
from fedml_tpu.data.synthetic import synthetic_classification


def _data():
    return synthetic_classification(
        num_clients=12,
        num_classes=5,
        feat_shape=(6,),
        samples_per_client=20,
        partition_method="hetero",
        seed=3,
    )


def test_store_batch_bitmatches_host_stacking():
    """The on-device gather must produce exactly the batch stack_clients
    builds on host (same seed, same bucket contract) — the store is a
    transport optimization, never a math change."""
    data = _data()
    store = DeviceDataStore(data)
    sampled = [0, 3, 7, 11]
    for seed in (0, 9):
        host = stack_clients(data, sampled, 8, seed=seed, pad_bucket=2)
        dev = store.round_batch(sampled, 8, seed=seed, pad_bucket=2)
        np.testing.assert_array_equal(np.asarray(dev.x), host.x)
        np.testing.assert_array_equal(np.asarray(dev.y), host.y)
        np.testing.assert_array_equal(np.asarray(dev.mask), host.mask)
        np.testing.assert_array_equal(np.asarray(dev.num_samples), host.num_samples)


def test_fedavg_store_matches_host_path():
    """A FedAvg run with device_cache on == the same run with it off."""
    import jax

    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.models import create_model

    data = _data()
    model = create_model("lr", "synthetic", (6,), 5)
    rows = {}
    for cache in (True, False):
        cfg = RunConfig(
            data=DataConfig(batch_size=8, device_cache=cache),
            fed=FedConfig(
                client_num_in_total=12, client_num_per_round=4, comm_round=3
            ),
            train=TrainConfig(lr=0.1),
            model="lr",
        )
        api = FedAvgAPI(cfg, data, model)
        assert (api._store is not None) == cache
        for r in range(3):
            api.train_round(r)
        rows[cache] = api.global_vars
    for a, b in zip(
        jax.tree_util.tree_leaves(rows[True]), jax.tree_util.tree_leaves(rows[False])
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_bf16_compute_dtype_learns_and_keeps_fp32_master():
    """bfloat16 compute policy: params stay fp32 (master weights), the model
    still reaches the same accuracy band as fp32 on an easy problem."""
    import jax.numpy as jnp

    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.models import create_model

    data = _data()
    model = create_model("lr", "synthetic", (6,), 5)
    accs = {}
    for dt in ("float32", "bfloat16"):
        cfg = RunConfig(
            data=DataConfig(batch_size=8),
            fed=FedConfig(
                client_num_in_total=12, client_num_per_round=12, comm_round=25
            ),
            train=TrainConfig(lr=0.2, compute_dtype=dt),
            model="lr",
        )
        api = FedAvgAPI(cfg, data, model)
        for r in range(25):
            api.train_round(r)
        import jax

        for leaf in jax.tree_util.tree_leaves(api.global_vars):
            assert leaf.dtype == jnp.float32  # master weights never degrade
        _, accs[dt] = api.evaluate_global()
    assert accs["bfloat16"] > 0.75
    assert abs(accs["bfloat16"] - accs["float32"]) < 0.1
