"""Client-level DP-FedAvg + RDP accountant (fedml_tpu/privacy/) — the
accounted upgrade over the reference's ad-hoc weak-DP noise
(robust_aggregation.py:38-55, which never reports an epsilon)."""

import math
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model
from fedml_tpu.privacy import (
    DpConfig,
    DPFedAvgAPI,
    RdpAccountant,
    rdp_subsampled_gaussian,
)
from fedml_tpu.privacy.dp_fedavg import clip_update_tree


# ---------------------------------------------------------------- accountant
def test_rdp_reduces_to_plain_gaussian_at_q1():
    """Internal consistency: at q=1 the subsampled bound must equal the
    analytic Gaussian RDP alpha/(2 sigma^2) exactly."""
    for sigma in (0.5, 1.0, 4.0):
        for alpha in (2, 8, 64):
            assert rdp_subsampled_gaussian(1.0, sigma, alpha) == pytest.approx(
                alpha / (2 * sigma**2)
            )


def test_rdp_monotonicity():
    """More rounds, more sampling, or less noise => more epsilon."""
    def eps(q, z, rounds):
        a = RdpAccountant()
        a.step(q, z, rounds=rounds)
        return a.epsilon(1e-5)[0]

    assert eps(0.1, 1.0, 10) < eps(0.1, 1.0, 100) < eps(0.1, 1.0, 1000)
    assert eps(0.01, 1.0, 100) < eps(0.1, 1.0, 100) < eps(0.5, 1.0, 100)
    assert eps(0.1, 4.0, 100) < eps(0.1, 1.0, 100) < eps(0.1, 0.6, 100)


def test_rdp_subsampling_amplifies():
    """Privacy amplification: q < 1 must beat the unsampled mechanism."""
    a_sub, a_full = RdpAccountant(), RdpAccountant()
    a_sub.step(0.05, 1.0, rounds=100)
    a_full.step(1.0, 1.0, rounds=100)
    assert a_sub.epsilon(1e-5)[0] < a_full.epsilon(1e-5)[0] / 3


def test_rdp_input_validation():
    with pytest.raises(ValueError):
        rdp_subsampled_gaussian(1.5, 1.0, 2)
    with pytest.raises(ValueError):
        rdp_subsampled_gaussian(0.5, 0.0, 2)
    with pytest.raises(ValueError):
        rdp_subsampled_gaussian(0.5, 1.0, 1)
    with pytest.raises(ValueError):
        RdpAccountant().epsilon(0.0)


# ---------------------------------------------------------------- clipping
def test_clip_update_tree_bounds_full_norm():
    g = {"a": jnp.zeros((3,)), "b": jnp.zeros((2, 2))}
    l = {"a": jnp.full((3,), 10.0), "b": jnp.full((2, 2), -10.0)}
    c = clip_update_tree(l, g, clip_norm=1.0)
    total = math.sqrt(
        sum(float(jnp.sum(jnp.square(x))) for x in jax.tree_util.tree_leaves(c))
    )
    assert total == pytest.approx(1.0, rel=1e-5)
    # a small update passes through unchanged
    s = {"a": jnp.full((3,), 0.01), "b": jnp.full((2, 2), 0.01)}
    c2 = clip_update_tree(s, g, clip_norm=1.0)
    for x, y in zip(
        jax.tree_util.tree_leaves(c2), jax.tree_util.tree_leaves(s)
    ):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


# ---------------------------------------------------------------- round/API
def _cfg(rounds=3, per_round=4, total=8):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=total, client_num_per_round=per_round,
            comm_round=rounds, epochs=1, frequency_of_the_test=10_000,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def _data_model():
    data = synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(6,), samples_per_client=16,
        partition_method="homo", ragged=False, seed=0,
    )
    return data, create_model("lr", "synthetic", (6,), 3)


def test_zero_noise_huge_clip_equals_uniform_mean_fedavg():
    """Degenerate-config oracle: z->0, S->inf and q=1 (per_round == total,
    so the Poisson draw includes everyone surely) turn DP-FedAvg into
    plain FedAvg with UNIFORM weights — with equal shard sizes that is
    exactly the sample-weighted FedAvg round."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI

    data, model = _data_model()
    # clip far above any real update norm (but not so large that the
    # noise stddev z*S/m becomes visible even at tiny z)
    dp_api = DPFedAvgAPI(
        _cfg(per_round=8), data, model,
        dp=DpConfig(clip_norm=1e4, noise_multiplier=1e-15),
    )
    plain = FedAvgAPI(_cfg(per_round=8), data, model)
    for r in range(3):
        dp_api.train_round(r)
        plain.train_round(r)
    for a, b in zip(
        jax.tree_util.tree_leaves(dp_api.global_vars),
        jax.tree_util.tree_leaves(plain.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


_TEST_SECRET = 0xDEADBEEF_CAFEBABE_0123456789ABCDEF  # 125-bit repro secret


def test_noise_is_applied_and_seeded():
    data, model = _data_model()
    mk = lambda: DPFedAvgAPI(
        _cfg(rounds=1), data, model,
        dp=DpConfig(
            clip_norm=0.5, noise_multiplier=1.0, sample_secret=_TEST_SECRET
        ),
    )
    a, b = mk(), mk()
    a.train_round(0)
    b.train_round(0)
    # same seed => identical noised result (reproducible)
    for x, y in zip(
        jax.tree_util.tree_leaves(a.global_vars),
        jax.tree_util.tree_leaves(b.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # and it differs from the noiseless run
    c = DPFedAvgAPI(
        _cfg(rounds=1), data, model,
        dp=DpConfig(
            clip_norm=0.5, noise_multiplier=1e-12, sample_secret=_TEST_SECRET
        ),
    )
    c.train_round(0)
    diffs = [
        float(jnp.max(jnp.abs(x - y)))
        for x, y in zip(
            jax.tree_util.tree_leaves(a.global_vars),
            jax.tree_util.tree_leaves(c.global_vars),
        )
    ]
    assert max(diffs) > 1e-4


def test_dp_run_learns_and_reports_epsilon():
    data, model = _data_model()
    api = DPFedAvgAPI(
        _cfg(rounds=20, per_round=8), data, model,
        dp=DpConfig(clip_norm=2.0, noise_multiplier=0.3, delta=1e-5),
    )
    final = api.train()
    assert final["DP/epsilon"] > 0
    assert final["DP/rounds_accounted"] == 20
    _, acc = api.evaluate_global()
    assert acc > 0.8, f"DP run failed to learn: acc={acc}"
    # accounting matches a hand-composed ledger
    ref = RdpAccountant()
    ref.step(1.0, 0.3, rounds=20)
    assert final["DP/epsilon"] == pytest.approx(ref.epsilon(1e-5)[0], rel=1e-6)


def test_ledger_survives_checkpoint_roundtrip():
    """A resumed DP run must carry the PRE-crash privacy spend — a reset
    ledger would under-report epsilon for updates already released."""
    data, model = _data_model()
    dp = DpConfig(clip_norm=1.0, noise_multiplier=0.8)
    a = DPFedAvgAPI(_cfg(rounds=6), data, model, dp=dp)
    for r in range(6):
        a.train_round(r)
    state = a.checkpoint_state()
    b = DPFedAvgAPI(_cfg(rounds=6), data, model, dp=dp)
    b.restore_state(state)
    assert b.accountant.rounds == 6
    assert b.privacy_spent()["DP/epsilon"] == a.privacy_spent()["DP/epsilon"]
    # the sampling secret rides with the ledger: the resumed run continues
    # the SAME participation stream (a re-draw would fork the mechanism
    # away from the accounted one mid-run)
    assert b._sample_secret == a._sample_secret
    for r in range(6, 10):
        assert b._sample_clients(r).tolist() == a._sample_clients(r).tolist()


def test_dp_sampling_secret_is_os_entropy_not_config_seed():
    """Advisor r4 (medium): config.seed defaults to 0 and is public/reused
    (data shuffling, broadcast init), so the participation stream must
    come from OS entropy by default — two default-constructed APIs at the
    same config.seed draw DIFFERENT cohorts — and an explicit low-entropy
    secret must warn that amplification is void."""
    data, model = _data_model()
    a = DPFedAvgAPI(_cfg(), data, model)
    b = DPFedAvgAPI(_cfg(), data, model)
    assert a._sample_secret != b._sample_secret
    assert a._sample_secret.bit_length() > 64  # 128-bit draw
    cohorts_a = [a._sample_clients(r).tolist() for r in range(30)]
    cohorts_b = [b._sample_clients(r).tolist() for r in range(30)]
    assert cohorts_a != cohorts_b
    with pytest.warns(UserWarning, match="entropy"):
        DPFedAvgAPI(
            _cfg(), data, model,
            dp=DpConfig(sample_secret=0),  # the old config.seed default
        )
    # a high-entropy explicit secret (tests/repro/resume) does not warn
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        DPFedAvgAPI(
            _cfg(), data, model, dp=DpConfig(sample_secret=_TEST_SECRET)
        )


def test_cli_rejects_degenerate_dp_flags():
    from click.testing import CliRunner

    from fedml_tpu.cli import main

    base = ["--algorithm", "dp_fedavg", "--dataset", "synthetic",
            "--model", "lr", "--comm_round", "1"]
    for bad in (["--dp_noise_multiplier", "0"], ["--dp_clip", "-1"],
                ["--dp_delta", "0"]):
        result = CliRunner().invoke(main, base + bad)
        assert result.exit_code != 0, bad
        assert "dp_" in result.output, bad


def test_mesh_dp_matches_vmap():
    """DistributedDPFedAvgAPI (psum uniform mean + the same clip/noise
    hooks) == the single-chip DPFedAvgAPI at the same seed — the noise
    rng chain is identical, so results agree to float tolerance."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from fedml_tpu.parallel import DistributedDPFedAvgAPI

    data, model = _data_model()
    dp = DpConfig(clip_norm=0.5, noise_multiplier=0.7)
    sim = DPFedAvgAPI(_cfg(rounds=3, per_round=8), data, model, dp=dp)
    mesh = DistributedDPFedAvgAPI(
        _cfg(rounds=3, per_round=8), data, model, dp=dp
    )
    for r in range(3):
        sim.train_round(r)
        mesh.train_round(r)
    assert mesh.accountant.rounds == sim.accountant.rounds == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(mesh.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_mesh_dp_poisson_cohort_matches_vmap():
    """q < 1: realized Poisson cohorts vary per round and need NOT divide
    the mesh — padding rows are excluded by the aggregate's inclusion
    mask, so the mesh run still bit-matches the single-chip simulator."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from fedml_tpu.parallel import DistributedDPFedAvgAPI

    data, model = _data_model()
    # the two runtimes must draw the SAME Poisson cohorts to be comparable
    # — share an explicit repro secret (each would otherwise draw its own
    # OS-entropy stream)
    dp = DpConfig(
        clip_norm=0.5, noise_multiplier=0.7, sample_secret=_TEST_SECRET
    )
    sim = DPFedAvgAPI(_cfg(rounds=4, per_round=5), data, model, dp=dp)
    mesh = DistributedDPFedAvgAPI(
        _cfg(rounds=4, per_round=5), data, model, dp=dp
    )
    saw_nondivisible = False
    for r in range(4):
        sampled, _ = sim.train_round(r)
        mesh.train_round(r)
        saw_nondivisible |= len(sampled) % mesh.n_shards != 0
    for a, b in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(mesh.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )
    # the run must actually have exercised a cohort that doesn't divide
    # the mesh — otherwise this test silently degrades to the q=1 one
    assert saw_nondivisible


# ------------------------------------------------------------ Poisson sampler
def test_poisson_sampling_matches_accounted_q():
    """The executed inclusion frequency is the accounted q (LLN check),
    and the API's sampler and accountant share the same q object."""
    from fedml_tpu.privacy.dp_fedavg import poisson_client_sampling

    N, q = 64, 0.25
    hits = np.zeros(N)
    rounds = 400
    for r in range(rounds):
        hits[poisson_client_sampling(0, r, N, q)] += 1
    freq = hits / rounds
    # per-client binomial stddev ~ sqrt(q(1-q)/rounds) ~ 0.022
    assert abs(freq.mean() - q) < 0.01
    assert np.all(np.abs(freq - q) < 0.1)

    data, model = _data_model()
    api = DPFedAvgAPI(_cfg(), data, model)
    assert api.sampling == "poisson"
    assert api._q == pytest.approx(4 / 8)
    cohorts = [set(api._sample_clients(r).tolist()) for r in range(50)]
    sizes = [len(c) for c in cohorts]
    assert min(sizes) < 4 < max(sizes), "cohort sizes should vary (Poisson)"


def test_poisson_sampling_is_run_dependent_not_public():
    """The ADVICE-high fix: cohort draws must depend on the run seed, not
    the round index alone (a round-only seed is publicly predictable,
    voiding amplification), and must not touch numpy's global PRNG."""
    from fedml_tpu.privacy.dp_fedavg import poisson_client_sampling

    a = [poisson_client_sampling(0, r, 32, 0.3).tolist() for r in range(20)]
    b = [poisson_client_sampling(1, r, 32, 0.3).tolist() for r in range(20)]
    assert a != b, "different run seeds must draw different cohorts"
    # deterministic per (seed, round) — reproducibility/resume contract
    assert a == [
        poisson_client_sampling(0, r, 32, 0.3).tolist() for r in range(20)
    ]
    # global numpy stream untouched (np.random.seed would be the old sin)
    np.random.seed(123)
    before = np.random.get_state()[1].copy()
    poisson_client_sampling(7, 3, 32, 0.3)
    np.random.seed(123)
    assert np.array_equal(before, np.random.get_state()[1])

    with pytest.raises(ValueError):
        poisson_client_sampling(0, 0, 8, 0.0)
    with pytest.raises(ValueError):
        poisson_client_sampling(0, 0, 8, 1.5)


def test_dp_padding_invariance():
    """Padding the cohort axis further must not change the mechanism: the
    fixed-denominator aggregate excludes dummy rows exactly."""
    import fedml_tpu.privacy.dp_fedavg as dpmod

    data, model = _data_model()
    dp = DpConfig(
        clip_norm=0.5, noise_multiplier=0.9, sample_secret=_TEST_SECRET
    )
    a = DPFedAvgAPI(_cfg(rounds=2), data, model, dp=dp)
    b = DPFedAvgAPI(_cfg(rounds=2), data, model, dp=dp)
    orig = dpmod.bucket_cohort
    try:
        dpmod.bucket_cohort = lambda m: orig(m) * 2  # double the padding
        for r in range(2):
            b.train_round(r)
    finally:
        dpmod.bucket_cohort = orig
    for r in range(2):
        a.train_round(r)
    for x, y in zip(
        jax.tree_util.tree_leaves(a.global_vars),
        jax.tree_util.tree_leaves(b.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7
        )


def test_dp_empty_cohort_round_is_noise_only():
    """An empty Poisson draw is a legal round: w moves by noise only, and
    with z ~ 0 the model is unchanged."""
    data, model = _data_model()
    api = DPFedAvgAPI(
        _cfg(rounds=1), data, model,
        dp=DpConfig(clip_norm=1.0, noise_multiplier=1e-15),
    )
    api._sample_clients = lambda r: np.array([], dtype=np.int64)
    before = jax.tree_util.tree_map(np.asarray, api.global_vars)
    api.train_round(0)
    assert api.accountant.rounds == 1
    for x, y in zip(
        jax.tree_util.tree_leaves(before),
        jax.tree_util.tree_leaves(api.global_vars),
    ):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)


def test_cli_dp_fedavg_reachable():
    import json

    from click.testing import CliRunner

    from fedml_tpu.cli import main

    result = CliRunner().invoke(
        main,
        [
            "--algorithm", "dp_fedavg", "--dataset", "synthetic",
            "--model", "lr", "--client_num_in_total", "8",
            "--client_num_per_round", "4", "--comm_round", "3",
            "--batch_size", "8", "--lr", "0.1",
            "--dp_clip", "1.0", "--dp_noise_multiplier", "0.8",
        ],
    )
    assert result.exit_code == 0, result.output
    row = json.loads(result.output.strip().splitlines()[-1])
    assert row["DP/epsilon"] > 0 and row["DP/delta"] == 1e-5


def test_dp_secret_validation_and_legacy_checkpoint_warning():
    data, model = _data_model()
    with pytest.raises(ValueError, match="non-negative"):
        DPFedAvgAPI(_cfg(), data, model, dp=DpConfig(sample_secret=-1))
    with pytest.raises(ValueError, match="256 bits"):
        DPFedAvgAPI(_cfg(), data, model, dp=DpConfig(sample_secret=1 << 300))
    # a legacy checkpoint (no dp_sample_secret) resumes with a loud
    # warning that the participation stream forks here
    api = DPFedAvgAPI(_cfg(), data, model, dp=DpConfig(sample_secret=_TEST_SECRET))
    api.train_round(0)
    state = api.checkpoint_state()
    state.pop("dp_sample_secret")
    b = DPFedAvgAPI(_cfg(), data, model)
    with pytest.warns(UserWarning, match="forks"):
        b.restore_state(state)
    assert b.accountant.rounds == 1


def test_secret_word_encoding_roundtrips_and_is_jax_safe():
    """The secret<->words encoding must survive a pass through jnp (the
    multi-host broadcast path): uint32 words are immune to the silent
    64->32-bit truncation jax applies with x64 disabled."""
    from fedml_tpu.privacy.dp_fedavg import (
        _secret_to_words,
        _words_to_secret,
    )

    for sec in (0, 1, _TEST_SECRET, (1 << 128) - 1):
        words = _secret_to_words(sec)
        assert words.dtype == np.uint32
        assert _words_to_secret(words) == sec
        # through jnp and back (broadcast_one_to_all's transport)
        assert _words_to_secret(np.asarray(jnp.asarray(words))) == sec
    # decode follows the array's actual word width (defensive tolerance
    # for checkpoints touched by other tooling)
    wide = np.asarray([0xDEADBEEF_CAFEBABE, 0x1234], np.uint64)
    assert _words_to_secret(wide) == (0x1234 << 64) | 0xDEADBEEF_CAFEBABE
    with pytest.raises(ValueError, match="exceeds"):
        _secret_to_words(1 << 300)
