"""Spilled client-state store (algorithms/state_store.py) — SCAFFOLD and
Ditto past the HBM budget ride the disk tier the data layer already uses
(VERDICT r3 Weak #3: round 3 refused at 8 GiB while the repo's own scale
story ran 100k clients on the mmap data store)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.ditto import DittoAPI
from fedml_tpu.algorithms.scaffold import ScaffoldAPI
from fedml_tpu.algorithms.state_store import MmapClientState, resolve_state_store
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import create_model


def _cfg(rounds=3, per_round=4, total=8, state_store="auto", budget=8 << 30):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=total, client_num_per_round=per_round,
            comm_round=rounds, epochs=1, frequency_of_the_test=10_000,
            state_store=state_store, state_budget_bytes=budget,
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=0,
    )


def _data_model(total=8):
    data = synthetic_classification(
        num_clients=total, num_classes=3, feat_shape=(6,),
        samples_per_client=16, partition_method="homo", ragged=False, seed=0,
    )
    return data, create_model("lr", "synthetic", (6,), 3)


# ------------------------------------------------------------------- store
def test_mmap_state_lazy_init_and_roundtrip(tmp_path):
    init = {"a": np.full((3,), 7.0, np.float32), "b": np.zeros((2, 2), np.float32)}
    st = MmapClientState(init, n_clients=100, path=str(tmp_path / "s"))
    # untouched rows gather as the initial state — no write happened
    got = st.gather([5, 50])
    np.testing.assert_array_equal(got["a"], np.tile(init["a"], (2, 1)))
    assert st.initialized_count() == 0
    rows = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.ones((2, 2, 2), np.float32)}
    st.scatter([5, 50], rows)
    assert st.initialized_count() == 2
    back = st.gather([50, 5, 7])
    np.testing.assert_array_equal(back["a"][0], rows["a"][1])
    np.testing.assert_array_equal(back["a"][1], rows["a"][0])
    np.testing.assert_array_equal(back["a"][2], init["a"])  # still lazy
    # reopen (resume) — schema-checked, rows survive
    st.flush()
    st2 = MmapClientState(init, n_clients=100, path=str(tmp_path / "s"))
    np.testing.assert_array_equal(st2.gather([5])["a"][0], rows["a"][0])
    assert st2.initialized_count() == 2
    # schema mismatch refuses
    with pytest.raises(ValueError):
        MmapClientState(init, n_clients=99, path=str(tmp_path / "s"))


def test_resolve_state_store_modes():
    fed = FedConfig(state_store="auto", state_budget_bytes=1000)
    assert resolve_state_store(fed, 999) == "device"
    assert resolve_state_store(fed, 1001) == "mmap"
    assert resolve_state_store(FedConfig(state_store="mmap"), 1) == "mmap"
    with pytest.raises(ValueError):
        resolve_state_store(FedConfig(state_store="hbm"), 1)


# ---------------------------------------------------- bit-identical oracles
def test_scaffold_spilled_bitmatches_device_store():
    """The spilled run and the in-HBM run are the SAME math: gather and
    scatter are exact row copies, the in-program compute is the same
    code. Exact equality, not allclose."""
    data, model = _data_model()
    dev = ScaffoldAPI(_cfg(state_store="device"), data, model)
    spill = ScaffoldAPI(_cfg(state_store="mmap"), data, model)
    assert dev._state_mode == "device" and spill._state_mode == "mmap"
    for r in range(3):
        dev.train_round(r)
        spill.train_round(r)
    for a, b in zip(
        jax.tree_util.tree_leaves(dev.global_vars),
        jax.tree_util.tree_leaves(spill.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree_util.tree_leaves(dev.c_server),
        jax.tree_util.tree_leaves(spill.c_server),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # per-client control rows match too
    sampled_all = sorted(
        {int(i) for r in range(3) for i in dev._round_plan(r)[0]}
    )
    rows = spill._c_store.gather(sampled_all)
    for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(
                lambda s: s[np.asarray(sampled_all)], dev.c_stack
            )
        ),
        jax.tree_util.tree_leaves(rows),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ditto_spilled_bitmatches_device_store():
    data, model = _data_model()
    dev = DittoAPI(_cfg(state_store="device"), data, model, lam=0.1)
    spill = DittoAPI(_cfg(state_store="mmap"), data, model, lam=0.1)
    for r in range(3):
        dev.train_round(r)
        spill.train_round(r)
    for a, b in zip(
        jax.tree_util.tree_leaves(dev.global_vars),
        jax.tree_util.tree_leaves(spill.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for i in range(8):
        for a, b in zip(
            jax.tree_util.tree_leaves(dev._personal_row(i)),
            jax.tree_util.tree_leaves(spill._personal_row(i)),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # personalized eval runs off the spilled store
    row = spill.personalized_test_on_clients()
    assert np.isfinite(row["Personalized/Acc"])


def test_spilled_checkpoint_resume_exact():
    """Kill-and-resume with the spilled store: the store directory is the
    durable state; a resumed run continues bit-identically."""
    data, model = _data_model()
    a = ScaffoldAPI(_cfg(rounds=6, state_store="mmap"), data, model)
    for r in range(3):
        a.train_round(r)
    state = a.checkpoint_state()
    gv = jax.device_get(a.global_vars)
    b = ScaffoldAPI(
        _cfg(rounds=6, state_store="mmap"), data, model
    )
    b.global_vars = jax.tree_util.tree_map(jnp.asarray, gv)
    b.restore_state(state)
    for r in range(3, 6):
        a.train_round(r)
        b.train_round(r)
    for x, y in zip(
        jax.tree_util.tree_leaves(a.global_vars),
        jax.tree_util.tree_leaves(b.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------- 10k scale
@pytest.mark.parametrize("api_cls,kw", [(ScaffoldAPI, {}), (DittoAPI, {"lam": 0.1})])
def test_stateful_10k_clients_spilled(api_cls, kw):
    """VERDICT r3 'do this' #2: 10k-client SCAFFOLD and Ditto in CI at
    reduced shape — a 1-byte budget forces the spill; rounds run, rows
    update, and nothing materializes the [N, ...] stack in RAM."""
    n = 10_000
    data = synthetic_classification(
        num_clients=64, num_classes=3, feat_shape=(6,),
        samples_per_client=8, partition_method="homo", ragged=False, seed=1,
    )
    # a 10k-client federation over 64 distinct shards (shared data keeps
    # the fixture small; the STATE store sees all 10k client ids)
    data = dataclasses.replace(
        data,
        client_x=[data.client_x[i % 64] for i in range(n)],
        client_y=[data.client_y[i % 64] for i in range(n)],
    )
    model = create_model("lr", "synthetic", (6,), 3)
    cfg = _cfg(rounds=2, per_round=16, total=n, state_store="auto", budget=1)
    api = api_cls(cfg, data, model, **kw)
    assert api._state_mode == "mmap"
    touched = set()
    for r in range(2):
        sampled, metrics = api.train_round(r)
        touched.update(int(i) for i in sampled)
        assert np.isfinite(float(metrics["loss_sum"]))
    store = api._c_store if api_cls is ScaffoldAPI else api._v_store
    assert store.n == n
    assert store.initialized_count() == len(touched)


def test_self_created_temp_store_dir_is_cleaned_up():
    """Advisor r4: a store spilling into a self-created temp dir must not
    leak N x |params| bytes of disk per run — the dir is removed when the
    store is garbage-collected. A user-supplied path is never removed."""
    import gc
    import os
    import tempfile

    import numpy as np

    from fedml_tpu.algorithms.state_store import MmapClientState

    init = {"w": np.zeros((4, 3), np.float32)}
    store = MmapClientState(init, n_clients=16)
    tmp_path = store.path
    store.scatter([1, 2], {"w": np.ones((2, 4, 3), np.float32)})
    assert os.path.isdir(tmp_path)
    del store
    gc.collect()
    assert not os.path.exists(tmp_path), "self-created temp dir leaked"

    user_dir = tempfile.mkdtemp(prefix="fedml_tpu_user_state_")
    store = MmapClientState(init, n_clients=16, path=user_dir)
    store.scatter([0], {"w": np.ones((1, 4, 3), np.float32)})
    del store
    gc.collect()
    assert os.path.isdir(user_dir), "user-supplied dir must survive"
    # and a fresh store resumes from it
    store2 = MmapClientState(init, n_clients=16, path=user_dir)
    assert store2.initialized_ids().tolist() == [0]


def test_empty_string_path_is_treated_as_unset():
    """FedConfig.state_dir defaults to "" — a store built with path=""
    must behave exactly like path=None: temp dir, cleaned up at gc."""
    import gc
    import os

    import numpy as np

    from fedml_tpu.algorithms.state_store import MmapClientState

    store = MmapClientState({"w": np.zeros((2,), np.float32)}, 4, path="")
    p = store.path
    assert p and os.path.isdir(p)
    del store
    gc.collect()
    assert not os.path.exists(p)


# ------------------------------------------------- spill x mesh composition
def test_scaffold_spilled_mesh_matches_single_chip():
    """The two scale stories COMPOSE (VERDICT r4 Weak #4): 100k-on-disk
    state AND the multi-chip mesh. The sharded cohort round at the same
    seed matches the single-chip spilled run to float tolerance, including
    cohorts that don't divide the mesh (dummy-padded rows)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from fedml_tpu.parallel import DistributedScaffoldAPI

    data, model = _data_model(total=12)
    cfg = _cfg(rounds=4, per_round=5, total=12, state_store="mmap")
    sim = ScaffoldAPI(cfg, data, model)
    mesh_api = DistributedScaffoldAPI(cfg, data, model)
    assert sim._state_mode == mesh_api._state_mode == "mmap"
    saw_nondivisible = False
    for r in range(4):
        sampled, m_sim = sim.train_round(r)
        _, m_mesh = mesh_api.train_round(r)
        saw_nondivisible |= len(sampled) % mesh_api.n_shards != 0
        np.testing.assert_allclose(
            float(m_sim["loss_sum"]), float(m_mesh["loss_sum"]), rtol=1e-5
        )
    assert saw_nondivisible  # 5 % 8 != 0 — padding actually exercised
    for name, a, b in (
        ("params", sim.global_vars, mesh_api.global_vars),
        ("c_server", sim.c_server, mesh_api.c_server),
        (
            "store_rows",
            sim._c_store.gather(np.arange(12)),
            mesh_api._c_store.gather(np.arange(12)),
        ),
    ):
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6,
                err_msg=name,
            )
    assert sim._c_store.initialized_ids().tolist() == \
        mesh_api._c_store.initialized_ids().tolist()


def test_ditto_spilled_mesh_matches_single_chip():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from fedml_tpu.parallel import DistributedDittoAPI

    data, model = _data_model(total=12)
    cfg = _cfg(rounds=3, per_round=5, total=12, state_store="mmap")
    sim = DittoAPI(cfg, data, model, lam=0.1)
    mesh_api = DistributedDittoAPI(cfg, data, model, lam=0.1)
    assert sim._state_mode == mesh_api._state_mode == "mmap"
    for r in range(3):
        sim.train_round(r)
        mesh_api.train_round(r)
    for x, y in zip(
        jax.tree_util.tree_leaves(sim.global_vars),
        jax.tree_util.tree_leaves(mesh_api.global_vars),
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
        )
    for x, y in zip(
        jax.tree_util.tree_leaves(sim._v_store.gather(np.arange(12))),
        jax.tree_util.tree_leaves(mesh_api._v_store.gather(np.arange(12))),
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
        )


# ------------------------------------------------------- cohort prefetcher
def test_cohort_prefetcher_excludes_in_flight_rows(tmp_path):
    """The overlap contract: rows being scattered are excluded from the
    background read and re-fetched at take() AFTER the scatter landed —
    the prefetched cohort must reflect the post-scatter store exactly."""
    from fedml_tpu.algorithms.state_store import CohortPrefetcher

    init = {"w": np.zeros((2,), np.float32)}
    st = MmapClientState(init, n_clients=10, path=str(tmp_path / "s"))
    pf = CohortPrefetcher(st)
    # round r writes rows {1, 2}; round r+1 wants {2, 3} (overlap: 2)
    pf.launch(1, [2, 3], exclude={1, 2})
    pf._thread.join()  # background read done BEFORE the scatter below
    st.scatter([1, 2], {"w": np.asarray([[10, 10], [20, 20]], np.float32)})
    got = pf.take(1, [2, 3])
    np.testing.assert_array_equal(got["w"][0], [20, 20])  # post-scatter!
    np.testing.assert_array_equal(got["w"][1], [0, 0])
    # mismatched take falls back to a plain gather
    pf.launch(2, [4], exclude=set())
    got = pf.take(3, [5, 6])
    np.testing.assert_array_equal(got["w"], np.zeros((2, 2)))
    pf.cancel()
