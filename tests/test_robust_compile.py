"""Byzantine-robust aggregators as first-class cached programs (ISSUE 14):
the robust round dedupes through the ProgramCache with the RobustConfig in
its digest (no more wrap_uncached bypass), AOT-warms, and is byte-identical
to the opaque-hook reference and across warm/cold. The digest audit's
drop-field fuzz must fail on exactly the RobustConfig leaves (the scaffold
eta_g pin's analog)."""

import dataclasses

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg import make_fedavg_round
from fedml_tpu.algorithms.fedavg_robust import (
    RobustFedAvgAPI,
    make_defense_hooks,
    make_robust_fedavg_round,
)
from fedml_tpu.compile import ProgramCache, use_program_cache
from fedml_tpu.config import DataConfig, FedConfig, RunConfig, TrainConfig
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models import ModelDef
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.robustness import RobustConfig


def _cfg(comm_round=3):
    return RunConfig(
        data=DataConfig(batch_size=8),
        fed=FedConfig(
            client_num_in_total=8, client_num_per_round=6,
            comm_round=comm_round, epochs=1, frequency_of_the_test=100,
            client_parallelism="vmap",
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        seed=5,
    )


def _data():
    return synthetic_classification(
        num_clients=8, num_classes=3, feat_shape=(6,),
        samples_per_client=24, partition_method="homo", seed=7,
    )


def _model():
    return ModelDef(
        module=LogisticRegression(num_classes=3), input_shape=(6,),
        num_classes=3, name="lr",
    )


DEFENSES = [
    RobustConfig(defense_type="median"),
    RobustConfig(defense_type="trimmed_mean", num_byzantine=1),
    RobustConfig(defense_type="krum", num_byzantine=1),
    RobustConfig(defense_type="multi_krum", num_byzantine=1, multi_krum_m=2),
    RobustConfig(defense_type="weak_dp"),
]


@pytest.mark.parametrize(
    "robust", DEFENSES, ids=[d.defense_type for d in DEFENSES]
)
def test_robust_round_is_cached_not_bypassed(robust):
    """The describable robust= path lands in the ProgramCache with a
    digest (the historical hook-closure path had to wrap_uncached);
    a second identical factory call is a dedup HIT on the same object."""
    with use_program_cache(ProgramCache()) as cache:
        p1 = make_robust_fedavg_round(
            _model(), _cfg(), robust
        ).variant_for(None)
        assert p1.digest is not None, "robust round was bypassed"
        assert p1.key_fields["robust"] is robust
        p2 = make_robust_fedavg_round(
            _model(), _cfg(), robust
        ).variant_for(None)
        assert p2 is p1
        assert cache.stats()["bypassed"] == 0


def test_robust_digest_splits_on_every_config_leaf():
    """Each RobustConfig leaf that can shape the traced defense gets its
    own digest — trim_k/num_byzantine included (the eta_g hazard class)."""
    base = RobustConfig(defense_type="trimmed_mean", num_byzantine=1)
    variants = [
        dataclasses.replace(base, num_byzantine=2),
        dataclasses.replace(base, defense_type="median"),
        dataclasses.replace(base, defense_type="multi_krum"),
        dataclasses.replace(
            base, defense_type="multi_krum", multi_krum_m=2
        ),
        dataclasses.replace(base, defense_type="weak_dp", stddev=0.5),
        dataclasses.replace(base, defense_type="weak_dp", norm_bound=1.0),
    ]
    with use_program_cache(ProgramCache()):
        digests = [
            make_robust_fedavg_round(_model(), _cfg(), r)
            .variant_for(None).digest
            for r in [base] + variants
        ]
    assert len(set(digests)) == len(digests), digests


def test_explicit_hooks_and_robust_kwarg_are_exclusive():
    hooks = make_defense_hooks(RobustConfig(defense_type="median"))
    with pytest.raises(ValueError, match="not both"):
        make_fedavg_round(
            _model(), _cfg(), aggregate_fn=hooks[2],
            robust=RobustConfig(defense_type="median"),
        )


@pytest.mark.parametrize(
    "robust", DEFENSES, ids=[d.defense_type for d in DEFENSES]
)
def test_cached_robust_round_matches_opaque_hook_reference(robust):
    """Byte-identical numerics to the eager reference: the cached
    (robust=) program and the historical opaque-hook (wrap_uncached)
    program are the same math — one dispatch each, exact equality."""
    model, cfg = _model(), _cfg()
    gv = model.init(jax.random.PRNGKey(0))
    C = 6
    rng = np.random.default_rng(0)
    stacked = jax.tree_util.tree_map(
        lambda p: jax.numpy.asarray(
            np.repeat(np.asarray(p, np.float32)[None], C, axis=0)
            + rng.normal(0, 0.05, (C,) + np.asarray(p).shape).astype(
                np.float32
            )
        ),
        gv,
    )
    x = jax.numpy.asarray(rng.normal(size=(C, 2, 8, 6)).astype(np.float32))
    y = jax.numpy.asarray(rng.integers(0, 3, size=(C, 2, 8)).astype(np.int32))
    mask = jax.numpy.ones((C, 2, 8), np.float32)
    ns = jax.numpy.asarray(np.full((C,), 24, np.float32))
    keys = jax.random.split(jax.random.PRNGKey(1), C)
    noise_rng = jax.random.PRNGKey(2)

    with use_program_cache(ProgramCache()):
        cached = make_fedavg_round(model, cfg, donate=False, robust=robust)
        out_cached, met_cached = cached(
            gv, x, y, mask, ns, keys, noise_rng
        )
    with use_program_cache(ProgramCache()) as cache:
        post_train, post_aggregate, aggregate_fn = make_defense_hooks(robust)
        opaque = make_fedavg_round(
            model, cfg, donate=False, post_train=post_train,
            post_aggregate=post_aggregate, aggregate_fn=aggregate_fn,
        )
        out_ref, met_ref = opaque(gv, x, y, mask, ns, keys, noise_rng)
        # the historical path really did bypass the cache (the wrap is
        # counted when the variant builds at first dispatch)
        assert cache.stats()["bypassed"] >= 1
    for a, b in zip(
        jax.tree_util.tree_leaves(out_cached),
        jax.tree_util.tree_leaves(out_ref),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(met_cached["loss_sum"]) == float(met_ref["loss_sum"])


@pytest.mark.parametrize("defense", ["median", "trimmed_mean"])
@pytest.mark.recompile_budget(40)
def test_robust_warm_vs_cold_byte_parity(defense, recompile_sentinel):
    """AOT warmup of the robust round (now reachable — it used to bypass
    the compile layer entirely) changes nothing numerically: warmed and
    cold runs are byte-identical."""
    robust = RobustConfig(defense_type=defense, num_byzantine=1)
    data, model = _data(), _model()
    cold = RobustFedAvgAPI(_cfg(), data, model, robust=robust)
    cold.train()
    warm = RobustFedAvgAPI(_cfg(), data, model, robust=robust)
    rows = warm.warmup()
    assert any(k.startswith("compile/round") for k in rows), rows
    warm.train()
    for a, b in zip(
        jax.tree_util.tree_leaves(cold.global_vars),
        jax.tree_util.tree_leaves(warm.global_vars),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for rc, rw in zip(cold.history, warm.history):
        assert rc["Train/Loss"] == rw["Train/Loss"]


def test_digest_audit_drop_robust_fails_on_its_leaves():
    """The fuzzer really detects the hazard class this PR closes: with
    'robust' dropped from the digest, the audit must fail on exactly the
    RobustConfig perturbations (num_byzantine — the trim_k window — and
    defense_type), like the scaffold eta_g pin."""
    from fedml_tpu.analysis.digest_audit import audit_factory, default_specs

    spec = [
        s for s in default_specs() if s.name == "robust_fedavg_round"
    ][0]
    audit = audit_factory(spec, drop_digest_fields=frozenset({"robust"}))
    bad = {v.field for v in audit.violations}
    assert "@robust.num_byzantine" in bad, bad
    assert "@robust.defense_type" in bad, bad
    # with the field kept, the same spec audits clean
    clean = audit_factory(spec)
    assert not clean.violations, clean.render()
